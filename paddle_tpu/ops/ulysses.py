"""Ulysses-style all-to-all sequence parallelism.

The second sequence-parallel scheme SURVEY §2 item 12 calls for (ring
attention being the first): activations arrive sharded on the SEQUENCE axis;
an all_to_all over the 'sp' mesh axis re-shards them on the HEAD axis so each
device runs ordinary (full-sequence) attention for H/sp heads, and a reverse
all_to_all restores sequence sharding. Two collectives per attention instead
of sp ppermute hops — cheaper than the ring when H >= sp and the sequence
fits per-device HBM after the head split.

Reference analogue: fleet sep (sequence-parallel) alltoall path over NCCL;
here both all_to_alls ride the ICI via XLA's all_to_all.

Layout: [batch, seq_local, heads, head_dim] in and out (inside shard_map).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ulysses_attention_local", "ulysses_attention"]


def _seq_to_heads(x, axis_name):
    """[B, L/sp, H, D] -> [B, L, H/sp, D] via all_to_all over 'sp'."""
    # split the head axis into sp groups, exchange so each device keeps one
    # group but gathers every sequence shard
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _heads_to_seq(x, axis_name):
    """[B, L, H/sp, D] -> [B, L/sp, H, D] — inverse all_to_all."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention_local(q, k, v, axis_name="sp", causal=True, scale=None,
                            attention_fn=None):
    """Runs INSIDE shard_map. q,k,v: [B, L_local, H, D] (sequence-sharded).

    attention_fn(q, k, v, causal, scale) runs the per-device full-sequence
    attention; defaults to the Pallas flash kernel path (GQA-capable since
    the head split divides Hq and Hkv alike).
    """
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    qg = _seq_to_heads(q, axis_name)      # [B, L, H/sp, D]
    kg = _seq_to_heads(k, axis_name)
    vg = _seq_to_heads(v, axis_name)
    if attention_fn is None:
        from .attention import _flash
        out = _flash(qg, kg, vg, causal, scale)
    else:
        out = attention_fn(qg, kg, vg, causal, scale)
    return _heads_to_seq(out, axis_name)  # [B, L_local, H, D]


def ulysses_attention(q, k, v, mesh=None, axis_name="sp", causal=True,
                      batch_axes=("dp", "fsdp"), scale=None):
    """shard_map wrapper: q,k,v GLOBAL [B, L, H, D], sequence dim split over
    `axis_name`. Requires H % sp == 0."""
    from jax.sharding import PartitionSpec as P

    from ..distributed.mesh import compat_shard_map, get_mesh

    mesh = mesh or get_mesh()
    sp = mesh.shape[axis_name]
    for name, t in (("query", q), ("key", k), ("value", v)):
        if t.shape[2] % sp != 0:
            raise ValueError(f"{name} heads ({t.shape[2]}) must be divisible "
                             f"by the '{axis_name}' axis size ({sp}) for "
                             "Ulysses SP; use ring_attention otherwise")
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(ulysses_attention_local, axis_name=axis_name,
                           causal=causal, scale=scale)
    # check_vma=False: the vma checker can't see through pallas_call's
    # out_shape, so it would force the flash kernel onto the fallback path
    return compat_shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check=False)(q, k, v)
