"""Paged KV cache + paged attention for inference decode.

TPU-native analogue of the reference's paged attention path (vLLM-style
block KV management the reference exposes through fused decode ops). KV
lives in fixed-size pages in HBM; each sequence owns a list of page ids
(page_table). Decode-time attention gathers only that sequence's pages.

Shapes:
  k_pages/v_pages : (num_pages, page_size, H, D)
  page_table      : (B, max_pages)  int32 page ids (-1 = unused)
  seq_lens        : (B,)            int32 current lengths
  q               : (B, 1, H, D)    single decode step

The compute path is jnp (XLA fuses the gather + masked softmax well on TPU
for decode's tiny FLOP count — latency is HBM-bound on page reads). The
opt-in Pallas kernel (`use_kernel=True`) uses scalar-prefetch paging: the
page pool stays in HBM and the prefetched page_table drives the BlockSpec
index maps, so exactly one page of K/V is in VMEM per grid step regardless
of pool size (semantics verified against the jnp path in interpret mode;
note: some remote-compile toolchains are slow to build the
PrefetchScalarGridSpec lowering — the jnp default avoids that).
"""
import functools

import jax
import jax.numpy as jnp

from ._fallback import kernel_fallback
import numpy as np

__all__ = ["PagedKVCache", "paged_attention"]


class PagedKVCache:
    """Fixed-pool paged KV storage with host-side page allocation."""

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 dtype=jnp.bfloat16):
        self.page_size = page_size
        self.k_pages = jnp.zeros((num_pages, page_size, num_heads, head_dim), dtype)
        self.v_pages = jnp.zeros((num_pages, page_size, num_heads, head_dim), dtype)
        self._free = list(range(num_pages - 1, -1, -1))
        self.page_tables = {}   # seq id -> list of page ids
        self.seq_lens = {}

    def new_seq(self, seq_id):
        self.page_tables[seq_id] = []
        self.seq_lens[seq_id] = 0

    def _ensure_capacity(self, seq_id, new_len):
        need = (new_len + self.page_size - 1) // self.page_size
        table = self.page_tables[seq_id]
        while len(table) < need:
            if not self._free:
                raise RuntimeError("PagedKVCache out of pages")
            table.append(self._free.pop())

    def append(self, seq_id, k, v):
        """Append one step's K/V (1, H, D) for a sequence."""
        pos = self.seq_lens[seq_id]
        self._ensure_capacity(seq_id, pos + 1)
        page = self.page_tables[seq_id][pos // self.page_size]
        slot = pos % self.page_size
        self.k_pages = self.k_pages.at[page, slot].set(
            jnp.asarray(k, self.k_pages.dtype).reshape(self.k_pages.shape[2:]))
        self.v_pages = self.v_pages.at[page, slot].set(
            jnp.asarray(v, self.v_pages.dtype).reshape(self.v_pages.shape[2:]))
        self.seq_lens[seq_id] = pos + 1

    def free_seq(self, seq_id):
        self._free.extend(reversed(self.page_tables.pop(seq_id, [])))
        self.seq_lens.pop(seq_id, None)

    def batch_view(self, seq_ids):
        """Dense (page_table, seq_lens) arrays for a batch of sequences."""
        max_pages = max((len(self.page_tables[s]) for s in seq_ids), default=1)
        max_pages = max(max_pages, 1)
        table = np.full((len(seq_ids), max_pages), -1, np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            ids = self.page_tables[s]
            table[i, :len(ids)] = ids
            lens[i] = self.seq_lens[s]
        return jnp.asarray(table), jnp.asarray(lens)


def _paged_attention_ref(q, k_pages, v_pages, page_table, seq_lens, scale):
    b, _, h, d = q.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    safe_table = jnp.maximum(page_table, 0)
    # gather this batch's pages: (B, max_pages, page_size, H, D)
    k = k_pages[safe_table].reshape(b, max_pages * page_size, h, d)
    v = v_pages[safe_table].reshape(b, max_pages * page_size, h, d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page_size)
    valid = pos[None, :] < seq_lens[:, None]          # (B, K)
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, s_scr, acc_scr, *, scale, page_size, max_pages):
    """Grid (B, H, max_pages): ONE page of K/V in VMEM per step — the page
    pool stays in HBM and the scalar-prefetched page_table drives the
    BlockSpec index maps, so pallas pipelines page fetches with compute
    (no whole-pool VMEM blowup; the previous kernel mapped the entire pool
    per grid cell and silently fell back for any realistic pool size).
    Online-softmax state lives in VMEM scratch across the page steps."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(2)
    seq_len = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        s_scr[...] = jnp.zeros_like(s_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0, 0].astype(jnp.float32).reshape(1, -1) * scale  # (1, D)
    k = k_ref[0, :, 0, :].astype(jnp.float32)                      # (P, D)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    logits = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))   # (1, P)
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)
    logits = jnp.where(pos < seq_len, logits, -1e30)

    m_prev, s_prev, acc_prev = m_scr[...], s_scr[...], acc_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    p = jnp.exp(logits - m_new)
    corr = jnp.exp(m_prev - m_new)
    m_scr[...] = m_new
    s_scr[...] = s_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[...] = acc_prev * corr + p @ v

    @pl.when(j == max_pages - 1)
    def _emit():
        out = acc_scr[...] / jnp.maximum(s_scr[...], 1e-30)
        o_ref[0, 0, 0] = out[0].astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, page_table, seq_lens, scale=None,
                    use_kernel=False, interpret=None):
    """Decode attention over a paged KV cache. q: (B, 1, H, D)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if not use_kernel:
        return _paged_attention_ref(q, k_pages, v_pages, page_table,
                                    seq_lens, scale)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    try:
        return _paged_kernel_call(q, k_pages, v_pages, page_table, seq_lens,
                                  scale, interpret)
    except Exception as e:
        kernel_fallback("paged_attention", e)
        return _paged_attention_ref(q, k_pages, v_pages, page_table,
                                    seq_lens, scale)


def _paged_kernel_call(q, k_pages, v_pages, page_table, seq_lens, scale,
                       interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, _, h, d = q.shape
    n_pages, page_size = k_pages.shape[:2]
    max_pages = page_table.shape[1]

    def page_map(bi, hi, j, pt, lens):
        return (jnp.maximum(pt[bi, j], 0), 0, hi, 0)  # -1 (unused) -> page 0, masked

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # page_table, seq_lens
        grid=(b, h, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, 1, d), lambda bi, hi, j, pt, lens: (bi, 0, hi, 0)),
            pl.BlockSpec((1, page_size, 1, d), page_map),
            pl.BlockSpec((1, page_size, 1, d), page_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 1, d), lambda bi, hi, j, pt, lens: (bi, 0, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, page_size=page_size,
                          max_pages=max_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pages, v_pages)
