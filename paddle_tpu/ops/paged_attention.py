"""Paged KV cache + paged attention for inference decode.

TPU-native analogue of the reference's paged attention path (vLLM-style
block KV management the reference exposes through fused decode ops). KV
lives in fixed-size pages in HBM; each sequence owns a list of page ids
(page_table). Decode-time attention gathers only that sequence's pages.

Shapes:
  k_pages/v_pages : (num_pages, page_size, H, D)
  page_table      : (B, max_pages)  int32 page ids (-1 = unused)
  seq_lens        : (B,)            int32 current lengths
  q               : (B, 1, H, D)    single decode step

The compute path is jnp (XLA fuses the gather + masked softmax well on TPU
for decode's tiny FLOP count — latency is HBM-bound on page reads); a Pallas
kernel variant processes one (batch, head) per grid cell for long contexts.
"""
import functools

import jax
import jax.numpy as jnp

from ._fallback import kernel_fallback
import numpy as np

__all__ = ["PagedKVCache", "paged_attention"]


class PagedKVCache:
    """Fixed-pool paged KV storage with host-side page allocation."""

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 dtype=jnp.bfloat16):
        self.page_size = page_size
        self.k_pages = jnp.zeros((num_pages, page_size, num_heads, head_dim), dtype)
        self.v_pages = jnp.zeros((num_pages, page_size, num_heads, head_dim), dtype)
        self._free = list(range(num_pages - 1, -1, -1))
        self.page_tables = {}   # seq id -> list of page ids
        self.seq_lens = {}

    def new_seq(self, seq_id):
        self.page_tables[seq_id] = []
        self.seq_lens[seq_id] = 0

    def _ensure_capacity(self, seq_id, new_len):
        need = (new_len + self.page_size - 1) // self.page_size
        table = self.page_tables[seq_id]
        while len(table) < need:
            if not self._free:
                raise RuntimeError("PagedKVCache out of pages")
            table.append(self._free.pop())

    def append(self, seq_id, k, v):
        """Append one step's K/V (1, H, D) for a sequence."""
        pos = self.seq_lens[seq_id]
        self._ensure_capacity(seq_id, pos + 1)
        page = self.page_tables[seq_id][pos // self.page_size]
        slot = pos % self.page_size
        self.k_pages = self.k_pages.at[page, slot].set(
            jnp.asarray(k, self.k_pages.dtype).reshape(self.k_pages.shape[2:]))
        self.v_pages = self.v_pages.at[page, slot].set(
            jnp.asarray(v, self.v_pages.dtype).reshape(self.v_pages.shape[2:]))
        self.seq_lens[seq_id] = pos + 1

    def free_seq(self, seq_id):
        self._free.extend(reversed(self.page_tables.pop(seq_id, [])))
        self.seq_lens.pop(seq_id, None)

    def batch_view(self, seq_ids):
        """Dense (page_table, seq_lens) arrays for a batch of sequences."""
        max_pages = max((len(self.page_tables[s]) for s in seq_ids), default=1)
        max_pages = max(max_pages, 1)
        table = np.full((len(seq_ids), max_pages), -1, np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            ids = self.page_tables[s]
            table[i, :len(ids)] = ids
            lens[i] = self.seq_lens[s]
        return jnp.asarray(table), jnp.asarray(lens)


def _paged_attention_ref(q, k_pages, v_pages, page_table, seq_lens, scale):
    b, _, h, d = q.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    safe_table = jnp.maximum(page_table, 0)
    # gather this batch's pages: (B, max_pages, page_size, H, D)
    k = k_pages[safe_table].reshape(b, max_pages * page_size, h, d)
    v = v_pages[safe_table].reshape(b, max_pages * page_size, h, d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page_size)
    valid = pos[None, :] < seq_lens[:, None]          # (B, K)
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_kernel(q_ref, kp_ref, vp_ref, pt_ref, len_ref, o_ref, *,
                  scale, page_size, max_pages):
    """One (batch, head) per grid cell; loops pages with masking. All
    intermediates are kept 2-D (Mosaic requires >=2-D vector shapes)."""
    from jax.experimental import pallas as pl

    q = q_ref[0, 0, 0].astype(jnp.float32).reshape(1, -1) * scale  # (1, D)
    d = q.shape[1]
    seq_len = len_ref[0]
    m = jnp.full((1, 1), -1e30, jnp.float32)
    s = jnp.zeros((1, 1), jnp.float32)
    acc = jnp.zeros((1, d), jnp.float32)

    def body(i, carry):
        m, s, acc = carry
        page = pt_ref[0, i]
        k = kp_ref[pl.dslice(page, 1), :, 0, :][0].astype(jnp.float32)  # (P, D)
        v = vp_ref[pl.dslice(page, 1), :, 0, :][0].astype(jnp.float32)
        logits = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))    # (1, P)
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        logits = jnp.where(pos < seq_len, logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        s_new = s * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + p @ v
        return m_new, s_new, acc_new

    n_live = (seq_len + page_size - 1) // page_size
    m, s, acc = jax.lax.fori_loop(0, n_live, body, (m, s, acc))
    o_ref[0, 0, 0] = (acc / jnp.maximum(s, 1e-30))[0].astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, page_table, seq_lens, scale=None,
                    use_kernel=False, interpret=None):
    """Decode attention over a paged KV cache. q: (B, 1, H, D)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if not use_kernel:
        return _paged_attention_ref(q, k_pages, v_pages, page_table,
                                    seq_lens, scale)
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, _, h, d = q.shape
    n_pages, page_size = k_pages.shape[:2]
    max_pages = page_table.shape[1]
    try:
        return _paged_kernel_call(q, k_pages, v_pages, page_table, seq_lens,
                                  scale, interpret)
    except Exception as e:
        kernel_fallback("paged_attention", e)
        return _paged_attention_ref(q, k_pages, v_pages, page_table,
                                    seq_lens, scale)


def _paged_kernel_call(q, k_pages, v_pages, page_table, seq_lens, scale,
                       interpret):
    from jax.experimental import pallas as pl

    b, _, h, d = q.shape
    n_pages, page_size = k_pages.shape[:2]
    max_pages = page_table.shape[1]
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, page_size=page_size,
                          max_pages=max_pages),
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((n_pages, page_size, 1, d), lambda i, j: (0, 0, j, 0)),
            pl.BlockSpec((n_pages, page_size, 1, d), lambda i, j: (0, 0, j, 0)),
            pl.BlockSpec((1, max_pages), lambda i, j: (i, 0)),
            pl.BlockSpec((1,), lambda i, j: (i,)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, 0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        interpret=interpret,
    )(q, k_pages, v_pages, page_table.astype(jnp.int32),
      seq_lens.astype(jnp.int32))
