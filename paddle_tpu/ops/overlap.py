"""Chunked collective-matmul primitives — hide the tensor-parallel wire
behind the MXU (T3-style compute/collective decomposition, arxiv
2401.16677).

A bulk tensor-parallel matmul serializes: the psum/all-gather cannot
start until the whole dot finishes, and nothing computes while the wire
drains — exactly what the Schedule Doctor's COLL-SERIALIZED lint
convicts.  These primitives split the matmul's FREE (non-contracted)
dimension into ``n_chunks`` tiles and ring-step each tile's transfer
(``lax.ppermute``) while the NEXT tile's matmul runs: chunk *t*'s
permutes and chunk *t+1*'s dot share no data edge, so the two-stream
schedule (and the real chip) overlap them.

Bit-identity contract (the repo's twin discipline): every element's
reduction keeps the identical participant order as the bulk collective,
so the chunked result is **bit-identical** to the bulk twin, per dtype.
The facts this leans on, pinned by tests/test_overlap.py:

* XLA CPU's ``psum``/``psum_scatter`` reduce in ascending device-index
  order; an explicit ring that reorders received pieces by source
  index and left-folds ascending reproduces it bit-exactly.
* sub-f32 floats (bf16/f16) accumulate in f32 with ONE final cast —
  per-step narrow adds do NOT match the bulk collective.  For the
  MATMUL reductions XLA goes further: it fuses ``psum(x @ w)`` so the
  all-reduce consumes the dot's UNROUNDED f32 partials (no bf16
  rounding between dot and reduce) — so the chunked paths compute
  their partial dots with ``preferred_element_type=f32``, exchange f32
  tiles, and cast once after the fold.  That doubles the sub-f32 wire
  payload versus a narrow-wire collective: the price of exactness.
* a column- or row-tiled matmul is bit-identical to the full matmul
  (the K-contraction order per output element is tile-independent).

Wire accounting: the divisible-free-dim path decomposes the all-reduce
into reduce-scatter + all-gather rings — per-device wire is exactly the
bulk psum's ring cost, 2(p-1)/p x payload, now in n_chunks x p
schedulable pieces.  The indivisible fallback exchanges full partials
((p-1) x payload): correct, but wire-heavier — keep free dims divisible
by the axis size where throughput matters.

Public wrappers (``overlap_*``) take GLOBAL arrays and wrap
``distributed.mesh.compat_shard_map`` over one named axis; the
``chunked_*`` bodies are usable directly inside an existing shard_map
(or a ``make_jaxpr(axis_env=...)`` capture).  ``impl="bulk"`` keeps the
jnp bulk reference as the A/B path behind a flag.
"""
import jax
import jax.numpy as jnp

__all__ = [
    "overlap_matmul_all_reduce", "overlap_matmul_reduce_scatter",
    "overlap_all_gather_matmul", "chunked_matmul_all_reduce",
    "chunked_matmul_reduce_scatter", "chunked_all_gather_matmul",
    "chunked_all_reduce",
]


def _axis_size(axis):
    """Participant count of a named axis (concrete at trace time)."""
    return int(jax.lax.psum(1, axis))


def _acc_dtype(dtype):
    """Accumulation dtype matching the bulk collective: sub-f32 floats
    widen to f32 (one final cast back), everything else is exact."""
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating) and dtype.itemsize < 4:
        return jnp.float32
    return dtype


def _tile_bounds(n, n_chunks):
    """n_chunks contiguous tile boundaries over ``n`` columns; ragged
    tails allowed (last tiles absorb the remainder), clamped so every
    tile is non-empty."""
    n_chunks = max(1, min(int(n_chunks), int(n)))
    return [(i * n) // n_chunks for i in range(n_chunks + 1)]


def _shift_perm(p, s):
    """ppermute pairs sending each device's value s hops up the ring
    (device d receives from (d - s) % p)."""
    return [(i, (i + s) % p) for i in range(p)]


def _ring_pieces(x, axis, p):
    """All participants' values of ``x``, collected by p-1 single-hop
    ring rotations. pieces[s] arrived from device (idx - s) % p."""
    perm = _shift_perm(p, 1)
    pieces = [x]
    buf = x
    for _ in range(p - 1):
        buf = jax.lax.ppermute(buf, axis, perm)
        pieces.append(buf)
    return pieces


def _by_source(pieces, axis):
    """Reorder ring pieces (pieces[s] from device (idx - s) % p) into
    ascending SOURCE-device order — the participant order the bulk
    collective reduces in."""
    p = len(pieces)
    stacked = jnp.stack(pieces)
    order = (jax.lax.axis_index(axis) - jnp.arange(p)) % p
    return jnp.take(stacked, order, axis=0)


def _ascending_sum(by_src, out_dtype):
    """Left-fold ``by_src`` ([p, ...], source-ascending) exactly the way
    the bulk collective does: f32 accumulation for sub-f32 floats, one
    final cast."""
    acc_dt = _acc_dtype(out_dtype)
    acc = by_src[0].astype(acc_dt)
    for j in range(1, by_src.shape[0]):
        acc = acc + by_src[j].astype(acc_dt)
    return acc.astype(out_dtype)


def _rs_tiles(x, w, axis, p, n_chunks):
    """Chunked matmul + reduce-scatter over the free (last) dim.

    The free dim N is first split into the p destination blocks the
    bulk ``psum_scatter(..., tiled=True)`` hands out (device j keeps
    columns [j*N/p, (j+1)*N/p)), then each block into n_chunks
    sub-tiles.  Per sub-tile every device computes its partial for ALL
    p destinations (one dot over the p strided column groups — tile
    t+1's dot overlaps tile t's exchange), exchanges partials so each
    destination receives every source's contribution, and left-folds
    them in ascending source order.  Returns the list of this device's
    reduced sub-tiles ([..., wt] each; concatenated they are its
    destination block)."""
    nfree = w.shape[-1]
    nb = nfree // p
    bounds = _tile_bounds(nb, n_chunks)
    idx = jax.lax.axis_index(axis)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    acc_dt = _acc_dtype(out_dtype)
    out_tiles = []
    for t in range(len(bounds) - 1):
        t0, t1 = bounds[t], bounds[t + 1]
        wcols = jnp.concatenate(
            [jax.lax.slice_in_dim(w, j * nb + t0, j * nb + t1, axis=-1)
             for j in range(p)], axis=-1)
        # partials stay in the accumulation dtype end-to-end: the bulk
        # twin's fused dot+psum reduces UNROUNDED f32 partials
        y = jnp.dot(x, wcols, preferred_element_type=acc_dt)
        blocks = jnp.stack(jnp.split(y, p, axis=-1))    # [p, ..., wt]
        # step s: send my partial for destination (idx+s), receive
        # source (idx-s)'s partial for me
        recvs = [jax.lax.dynamic_index_in_dim(blocks, idx, 0,
                                              keepdims=False)]
        for s in range(1, p):
            send = jax.lax.dynamic_index_in_dim(
                blocks, (idx + s) % p, 0, keepdims=False)
            recvs.append(jax.lax.ppermute(send, axis, _shift_perm(p, s)))
        out_tiles.append(_ascending_sum(_by_source(recvs, axis),
                                        out_dtype))
    return out_tiles


# ----------------------------------------------------------- body level


def chunked_all_reduce(x, axis, impl="ring"):
    """``psum(x, axis)`` as an explicit full-exchange ring with the
    ascending source-order fold — the per-bucket building block of the
    Trainer's dp grad reduction (each bucket's ring steps overlap the
    optimizer update consuming the previous bucket).  Bit-identical to
    the bulk psum; wire is (p-1) x payload (== the psum ring's
    2(p-1)/p at p=2, heavier above)."""
    p = _axis_size(axis)
    if p == 1:
        return x
    if impl == "bulk":
        return jax.lax.psum(x, axis)
    return _ascending_sum(_by_source(_ring_pieces(x, axis, p), axis),
                          x.dtype)


def chunked_matmul_all_reduce(x, w, axis, n_chunks=4, impl="ring"):
    """``psum(x @ w, axis)`` with the wire decomposed into per-chunk
    ring steps that overlap the neighbouring chunks' matmuls.  Call
    inside a shard_map over ``axis``: x [..., K_local] (contraction dim
    sharded), w [K_local, N]; the result is the full [..., N],
    replicated over ``axis``, bit-identical to the bulk psum."""
    y_dtype = jnp.result_type(x.dtype, w.dtype)
    p = _axis_size(axis)
    if impl == "bulk":
        y = x @ w
        return jax.lax.psum(y, axis) if p > 1 else y
    if p == 1:
        return x @ w                      # 1-participant: zero wire
    nfree = w.shape[-1]
    if nfree % p == 0:
        # reduce-scatter + all-gather rings: bulk-psum ring wire
        # (2(p-1)/p x payload) in n_chunks x p schedulable pieces.
        # All-gather each reduced sub-tile as soon as its fold lands,
        # then reassemble the bulk column order (block j's sub-tile t
        # sits at columns [j*N/p + t0, j*N/p + t1)).
        tiles = _rs_tiles(x, w, axis, p, n_chunks)
        cols = [[] for _ in range(p)]
        for red in tiles:
            by_src = _by_source(_ring_pieces(red, axis, p), axis)
            for j in range(p):
                cols[j].append(by_src[j])
        return jnp.concatenate([piece for j in range(p)
                                for piece in cols[j]], axis=-1)
    # indivisible free dim: ONE bulk dot (XLA CPU's gemm remainder
    # micro-kernel makes column-tiled dots of odd widths drift by a
    # ulp, so tiling the dot here would break the twin pin), then
    # exchange full per-chunk partial SLICES — the transfers still
    # decompose and overlap other compute, at (p-1) x payload wire
    # (heavier than the ring pair; keep free dims divisible by the
    # axis size where throughput matters)
    y = jnp.dot(x, w, preferred_element_type=_acc_dtype(y_dtype))
    bounds = _tile_bounds(nfree, n_chunks)
    tiles = []
    for t in range(len(bounds) - 1):
        yt = jax.lax.slice_in_dim(y, bounds[t], bounds[t + 1], axis=-1)
        tiles.append(_ascending_sum(
            _by_source(_ring_pieces(yt, axis, p), axis), y_dtype))
    return jnp.concatenate(tiles, axis=-1)


def chunked_matmul_reduce_scatter(x, w, axis, n_chunks=4, impl="ring"):
    """``psum_scatter(x @ w, axis, scatter_dimension=-1, tiled=True)``
    with per-chunk ring exchange.  Requires the free dim divisible by
    the axis size (as the tiled bulk twin does); returns this device's
    [..., N/p] destination block, bit-identical to the bulk twin."""
    p = _axis_size(axis)
    if p == 1:
        return x @ w
    nfree = w.shape[-1]
    if nfree % p:
        raise ValueError(
            f"reduce_scatter free dim {nfree} not divisible by "
            f"axis '{axis}' size {p}")
    if impl == "bulk":
        y = x @ w
        return jax.lax.psum_scatter(y, axis, scatter_dimension=y.ndim - 1,
                                    tiled=True)
    return jnp.concatenate(_rs_tiles(x, w, axis, p, n_chunks), axis=-1)


def chunked_all_gather_matmul(x, w, axis, n_chunks=4, impl="ring"):
    """``all_gather(x, axis, axis=0, tiled=True) @ w`` with the gather
    decomposed into ring hops whose transfers overlap the previous
    piece's matmul.  x [M_local, ..., K] (dim 0 sharded), w local;
    returns [p*M_local, ..., N].  Row tiles beyond the p ring pieces
    (n_chunks > p) ride separate rings so transfer granularity keeps
    shrinking."""
    p = _axis_size(axis)
    if impl == "bulk":
        xg = (jax.lax.all_gather(x, axis, axis=0, tiled=True)
              if p > 1 else x)
        return xg @ w
    if p == 1:
        return x @ w
    m = x.shape[0]
    q = max(1, -(-int(n_chunks) // p))          # row tiles per ring piece
    bounds = _tile_bounds(m, q)
    rows = [[] for _ in range(p)]               # [source][tile] outputs
    for t in range(len(bounds) - 1):
        xt = jax.lax.slice_in_dim(x, bounds[t], bounds[t + 1], axis=0)
        outs = [piece @ w for piece in _ring_pieces(xt, axis, p)]
        by_src = _by_source(outs, axis)
        for j in range(p):
            rows[j].append(by_src[j])
    return jnp.concatenate([piece for j in range(p)
                            for piece in rows[j]], axis=0)


# -------------------------------------------------------- global level


def _resolve_mesh(mesh):
    if mesh is not None:
        return mesh
    from ..distributed.mesh import get_mesh
    return get_mesh()


def _wrap(body, mesh, axis, in_specs, out_specs):
    from ..distributed.mesh import compat_shard_map
    return compat_shard_map(body, mesh, in_specs=in_specs,
                            out_specs=out_specs, axis_names={axis},
                            check=False)


def overlap_matmul_all_reduce(x, w, axis="tp", n_chunks=4, mesh=None,
                              impl="ring"):
    """Row-parallel matmul + all-reduce over ``axis`` (the tp GPT
    proj/fc2 sites): x [..., K] with K sharded over ``axis``, w [K, N]
    row-sharded; returns the full [..., N] replicated over ``axis``,
    bit-identical to GSPMD's dot+psum.  ``impl="bulk"`` is the
    serialized A/B twin."""
    from jax.sharding import PartitionSpec as P
    mesh = _resolve_mesh(mesh)
    if int(mesh.shape.get(axis, 1)) == 1:
        return x @ w
    in_specs = (P(*([None] * (x.ndim - 1) + [axis])), P(axis, None))
    out_specs = P(*([None] * x.ndim))
    return _wrap(
        lambda xs, ws: chunked_matmul_all_reduce(
            xs, ws, axis, n_chunks=n_chunks, impl=impl),
        mesh, axis, in_specs, out_specs)(x, w)


def overlap_matmul_reduce_scatter(x, w, axis="tp", n_chunks=4, mesh=None,
                                  impl="ring"):
    """Row-parallel matmul + reduce-scatter over ``axis``: like the
    all-reduce twin but each device keeps only its [..., N/p] block of
    the free dim (sequence-parallel boundaries)."""
    from jax.sharding import PartitionSpec as P
    mesh = _resolve_mesh(mesh)
    if int(mesh.shape.get(axis, 1)) == 1:
        return x @ w
    in_specs = (P(*([None] * (x.ndim - 1) + [axis])), P(axis, None))
    out_specs = P(*([None] * (x.ndim - 1) + [axis]))
    return _wrap(
        lambda xs, ws: chunked_matmul_reduce_scatter(
            xs, ws, axis, n_chunks=n_chunks, impl=impl),
        mesh, axis, in_specs, out_specs)(x, w)


def overlap_all_gather_matmul(x, w, axis="tp", n_chunks=4, mesh=None,
                              impl="ring"):
    """All-gather x along dim 0 over ``axis`` then matmul with the
    column-sharded w: x [M, ..., K] dim-0 sharded, w [K, N] with N
    sharded; returns [M_global, ..., N/p] per device."""
    from jax.sharding import PartitionSpec as P
    mesh = _resolve_mesh(mesh)
    if int(mesh.shape.get(axis, 1)) == 1:
        return x @ w
    in_specs = (P(axis, *([None] * (x.ndim - 1))), P(None, axis))
    out_specs = P(*([None] * (x.ndim - 1) + [axis]))
    return _wrap(
        lambda xs, ws: chunked_all_gather_matmul(
            xs, ws, axis, n_chunks=n_chunks, impl=impl),
        mesh, axis, in_specs, out_specs)(x, w)
