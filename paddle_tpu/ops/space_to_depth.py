"""Space-to-depth stem convolution (the MLPerf ResNet TPU trick).

A 7x7/stride-2 conv on 3-channel input starves the MXU: C=3 occupies 3
of 128 lanes. The exact-equivalent rewrite packs 2x2 spatial blocks into
channels (NHWC [N,H,W,3] -> [N,H/2,W/2,12]) and runs a 4x4/stride-1
conv with the correspondingly rearranged kernel — 4x the lane occupancy
and no strided window. Derivation (1D): with the 7-tap kernel zero-
padded to 8 taps on the left, out[i] = sum_m K[m] . y[i-2+m] over the
paired signal y[j] = (x[2j], x[2j+1]), i.e. a 4-tap conv with
asymmetric padding (2, 1). Bit-exact, checkpoint-compatible (consumes
the ORIGINAL [O,3,7,7] weight).

Reference counterpart: the stem conv lowering decisions in
paddle/phi/kernels/gpu conv kernels are cuDNN's problem; on TPU the
graph itself must present an MXU-friendly shape.
"""
import jax
import jax.numpy as jnp

__all__ = ["space_to_depth_stem_conv"]


def space_to_depth_stem_conv(x, w):
    """Exact equivalent of conv2d(x, w, stride=2, padding=3) for NHWC x
    [N,H,W,C] (H, W even) and OIHW w [O,C,7,7]."""
    N, H, W, C = x.shape
    O, Ci, kh, kw = w.shape
    assert (kh, kw) == (7, 7) and Ci == C and H % 2 == 0 and W % 2 == 0, (
        "space_to_depth_stem_conv handles the 7x7/s2 stem on even "
        f"spatial dims, got w {w.shape} x {x.shape}")
    # input: pack 2x2 blocks into channels, order (bu, bv, c)
    y = x.reshape(N, H // 2, 2, W // 2, 2, C)
    y = y.transpose(0, 1, 3, 2, 4, 5).reshape(N, H // 2, W // 2, 4 * C)
    # kernel: zero-pad 7->8 leading on both spatial dims, then fold the
    # 2x2 phase into the input-channel dim with the SAME (bu, bv, c) order
    w8 = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    wh = w8.transpose(2, 3, 1, 0)                    # [8, 8, C, O] HWIO
    K = wh.reshape(4, 2, 4, 2, C, O).transpose(0, 2, 1, 3, 4, 5)
    K = K.reshape(4, 4, 4 * C, O)
    from ..nn.functional.common import amp_compute_cast
    y = amp_compute_cast(y, K)          # same dtype rule as F.conv2d
    return jax.lax.conv_general_dilated(
        y, K.astype(y.dtype), window_strides=(1, 1),
        padding=((2, 1), (2, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
