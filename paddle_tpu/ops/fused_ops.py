"""Fused elementwise/reduction Pallas kernels.

TPU-native equivalents of the reference's fused CUDA ops:
- fused_softmax_cross_entropy ≈ phi softmax_with_cross_entropy kernel
  (paddle/phi/kernels/gpu/cross_entropy_kernel.cu): one pass over the vocab
  axis produces the loss; the backward is the classic (softmax - onehot) * g
  without materializing probabilities in fp32 HBM twice.
- fused_adamw ≈ fused_adam_op (paddle/fluid/operators/fused/fused_adam_op.cc):
  p/m/v updated in a single kernel launch per tensor.
- fused_dropout_residual_layer_norm ≈ fused_dropout_add_ln
  (paddle/fluid/operators/fused/fused_layernorm_residual_dropout_bias.h).

Each has a jnp reference; the Pallas path engages on TPU-friendly shapes and
falls back otherwise (same dispatch pattern as ops/attention.py).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._fallback import kernel_fallback

__all__ = ["fused_softmax_cross_entropy", "fused_adamw",
           "fused_dropout_residual_layer_norm"]


def _interpret_default():
    return jax.default_backend() == "cpu"


def can_fuse_xent(n, v):
    """True when the streaming CE kernel will engage: TPU backend, row blocks
    tile, and the vocab has a 128-multiple block divisor."""
    if jax.default_backend() == "cpu":
        return False
    if n <= 0 or n % 256 != 0:
        return False
    try:
        _pick_block_v(v)
        return True
    except ValueError:
        return False


def _pick_block_v(v):
    """Largest vocab block (multiple of 128, VMEM-friendly) dividing v."""
    for cand in (1024, 768, 512, 384, 256, 128):
        if v % cand == 0:
            return cand
    raise ValueError(f"vocab {v} has no 128-multiple block divisor")


# --------------------------------------------------------------------------
# fused softmax cross entropy
# --------------------------------------------------------------------------

def _xent_ref(logits, labels):
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits.astype(jnp.float32), labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return lse - picked


def _xent_fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_s, s_s, p_s, *,
                     block_v, n_vb):
    """Streaming online-softmax CE: the vocab axis is the innermost grid dim
    (TPU grid iterations run sequentially), carry lives in VMEM scratch —
    only one (block_n, block_v) logits tile is resident at a time."""
    from jax.experimental import pallas as pl

    rows = x_ref.shape[0]
    j = pl.program_id(1)
    lab = lab_ref[...]                         # (rows, 1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((rows, 1), -1e30, jnp.float32)
        s_s[...] = jnp.zeros((rows, 1), jnp.float32)
        p_s[...] = jnp.zeros((rows, 1), jnp.float32)

    x = x_ref[...].astype(jnp.float32)
    m = m_s[...]
    m_new = jnp.maximum(m, jnp.max(x, axis=-1, keepdims=True))
    s_s[...] = s_s[...] * jnp.exp(m - m_new) + jnp.sum(
        jnp.exp(x - m_new), axis=-1, keepdims=True)
    m_s[...] = m_new
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (rows, block_v), 1)
    hit = cols == lab
    p_s[...] = p_s[...] + jnp.sum(jnp.where(hit, x, 0.0), axis=-1, keepdims=True)

    @pl.when(j == n_vb - 1)
    def _fin():
        lse = m_s[...] + jnp.log(jnp.maximum(s_s[...], 1e-30))
        loss_ref[...] = lse - p_s[...]
        lse_ref[...] = lse


def _xent_bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref, *, block_v):
    from jax.experimental import pallas as pl

    rows = x_ref.shape[0]
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    lab = lab_ref[...]                          # (rows, 1)
    lse = lse_ref[...]                          # (rows, 1)
    g = g_ref[...]                              # (rows, 1)
    p = jnp.exp(x - lse)
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (rows, block_v), 1)
    onehot = (cols == lab).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_softmax_cross_entropy(logits, labels):
    """loss[i] = logsumexp(logits[i]) - logits[i, labels[i]] — (N, V) x (N,)."""
    loss, _ = _xent_fwd(logits, labels)
    return loss


def _xent_fwd_impl(logits, labels, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _interpret_default()
    from jax.experimental.pallas import tpu as pltpu

    n, v = logits.shape
    block_n = 256 if n % 256 == 0 else n
    block_v = _pick_block_v(v)
    n_vb = v // block_v
    loss, lse = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, block_v=block_v, n_vb=n_vb),
        grid=(n // block_n, n_vb),
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(n, 1))
    return loss[:, 0], lse


def _xent_fwd(logits, labels):
    try:
        loss, lse = _xent_fwd_impl(logits, labels)
    except Exception as e:
        kernel_fallback("fused_softmax_xent_fwd", e)
        loss = _xent_ref(logits, labels)
        lse = None
    return loss, (logits, labels, lse)


def _xent_bwd_impl(logits, labels, lse, g, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _interpret_default()
    n, v = logits.shape
    block_n = 256 if n % 256 == 0 else n
    block_v = _pick_block_v(v)
    return pl.pallas_call(
        functools.partial(_xent_bwd_kernel, block_v=block_v),
        grid=(n // block_n, v // block_v),
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(n, 1), lse.reshape(n, 1),
      g.reshape(n, 1))


def _xent_vjp_fwd(logits, labels):
    loss, res = _xent_fwd(logits, labels)
    return loss, res


def _xent_vjp_bwd(res, g):
    logits, labels, lse = res
    if lse is not None:
        try:
            return _xent_bwd_impl(logits, labels, lse, g), None
        except Exception as e:
            kernel_fallback("fused_softmax_xent_bwd", e)
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return ((p - onehot) * g[:, None]).astype(logits.dtype), None


fused_softmax_cross_entropy.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


# --------------------------------------------------------------------------
# fused AdamW update
# --------------------------------------------------------------------------

def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref, *,
                  lr, beta1, beta2, eps, weight_decay, bc1, bc2):
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    mhat = m_new / bc1
    vhat = v_new / bc2
    p_new = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p)
    po_ref[...] = p_new.astype(po_ref.dtype)
    mo_ref[...] = m_new.astype(mo_ref.dtype)
    vo_ref[...] = v_new.astype(vo_ref.dtype)


def fused_adamw(p, g, m, v, step, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                weight_decay=0.01, interpret=None):
    """One fused AdamW update; returns (p_new, m_new, v_new). `step` is the
    1-based step count used for bias correction (a python/static int)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _interpret_default()
    bc1 = 1.0 - beta1 ** step
    bc2 = 1.0 - beta2 ** step
    shape = p.shape
    flat = int(np.prod(shape)) if shape else 1
    args = [t.reshape(flat) for t in (p, g, m, v)]
    block = 65536 if flat % 65536 == 0 else flat
    try:
        po, mo, vo = pl.pallas_call(
            functools.partial(_adamw_kernel, lr=lr, beta1=beta1, beta2=beta2,
                              eps=eps, weight_decay=weight_decay, bc1=bc1, bc2=bc2),
            grid=(flat // block,),
            in_specs=[pl.BlockSpec((block,), lambda i: (i,))] * 4,
            out_specs=[pl.BlockSpec((block,), lambda i: (i,))] * 3,
            out_shape=[jax.ShapeDtypeStruct((flat,), p.dtype),
                       jax.ShapeDtypeStruct((flat,), m.dtype),
                       jax.ShapeDtypeStruct((flat,), v.dtype)],
            interpret=interpret,
        )(*args)
    except Exception as e:
        kernel_fallback("fused_adamw", e)
        pf, gf, mf, vf = (t.astype(jnp.float32) for t in args)
        mo = beta1 * mf + (1 - beta1) * gf
        vo = beta2 * vf + (1 - beta2) * gf * gf
        po = pf - lr * ((mo / bc1) / (jnp.sqrt(vo / bc2) + eps)
                        + weight_decay * pf)
        po, mo, vo = po.astype(p.dtype), mo.astype(m.dtype), vo.astype(v.dtype)
    return po.reshape(shape), mo.reshape(shape), vo.reshape(shape)


# --------------------------------------------------------------------------
# fused dropout + residual + layer norm
# --------------------------------------------------------------------------

def _dropout_res_ln_ref(x, residual, weight, bias, key, p, eps, training):
    if training and p > 0:
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
        x = jnp.where(keep, x / (1.0 - p), 0.0)
    h = x + residual
    h32 = h.astype(jnp.float32)
    mean = h32.mean(axis=-1, keepdims=True)
    var = h32.var(axis=-1, keepdims=True)
    out = (h32 - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype), h


def _dropout_res_ln_kernel(x_ref, r_ref, w_ref, b_ref, rng_ref, o_ref, h_ref,
                           *, p, eps, host_bits):
    """rng_ref is the per-call seed (TPU: in-kernel hardware PRNG draws the
    mask, nothing rides through HBM) or a precomputed uint32 bits block
    (host_bits=True: CPU/interpret, where the prng primitives have no
    lowering).  Everything downstream of `bits` is the same code either
    way, so interpret-mode tests assert the real threshold/scale/LN
    arithmetic."""
    from jax.experimental import pallas as pl
    x = x_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)
    if p > 0:
        if host_bits:
            bits = rng_ref[...]
        else:
            from jax.experimental.pallas import tpu as pltpu
            pltpu.prng_seed(rng_ref[0] + pl.program_id(0))
            bits = pltpu.prng_random_bits(x_ref.shape)
        thresh = jnp.asarray(int((1.0 - p) * (2 ** 32 - 1)), jnp.uint32)
        keep = bits.astype(jnp.uint32) <= thresh
        x = jnp.where(keep, x / (1.0 - p), 0.0)
    h = x + r
    mean = h.mean(axis=-1, keepdims=True)
    var = ((h - mean) ** 2).mean(axis=-1, keepdims=True)
    out = (h - mean) * jax.lax.rsqrt(var + eps)
    out = out * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)
    h_ref[...] = h.astype(h_ref.dtype)


def fused_dropout_residual_layer_norm(x, residual, weight, bias, p=0.1,
                                      eps=1e-5, seed=0, training=True,
                                      interpret=None):
    """out = LN(dropout(x) + residual); also returns the pre-LN sum (the
    residual stream the next block consumes). 2-D (rows, hidden) input."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _interpret_default()
    n, h = x.shape
    w = weight if weight is not None else jnp.ones((h,), x.dtype)
    b = bias if bias is not None else jnp.zeros((h,), x.dtype)
    block_n = 256 if n % 256 == 0 else n
    if h % 128 == 0:
        # interpret mode has no lowering for the TPU prng primitives:
        # draw the mask bits on the host there so the kernel's dropout
        # arithmetic still runs (and is asserted) on CPU
        host_bits = bool(interpret) and training and p > 0
        if host_bits:
            bits = jax.random.bits(jax.random.PRNGKey(seed), (n, h),
                                   jnp.uint32)
            rng_arg = bits
            rng_spec = pl.BlockSpec((block_n, h), lambda i: (i, 0))
        else:
            rng_arg = jnp.asarray([seed], jnp.int32)
            rng_spec = pl.BlockSpec((1,), lambda i: (0,))
        try:
            return tuple(pl.pallas_call(
                functools.partial(_dropout_res_ln_kernel,
                                  p=p if training else 0.0, eps=eps,
                                  host_bits=host_bits),
                grid=(n // block_n,),
                in_specs=[
                    pl.BlockSpec((block_n, h), lambda i: (i, 0)),
                    pl.BlockSpec((block_n, h), lambda i: (i, 0)),
                    pl.BlockSpec((h,), lambda i: (0,)),
                    pl.BlockSpec((h,), lambda i: (0,)),
                    rng_spec,
                ],
                out_specs=[
                    pl.BlockSpec((block_n, h), lambda i: (i, 0)),
                    pl.BlockSpec((block_n, h), lambda i: (i, 0)),
                ],
                out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                           jax.ShapeDtypeStruct((n, h), x.dtype)],
                interpret=interpret,
            )(x, residual, w, b, rng_arg))
        except Exception as e:
            kernel_fallback("fused_dropout_residual_ln", e)
    key = jax.random.PRNGKey(seed)
    return _dropout_res_ln_ref(x, residual, w, b, key, p, eps, training)
