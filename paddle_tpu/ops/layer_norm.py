"""Fused LayerNorm / RMSNorm Pallas kernels.

Replaces the reference's fused layer_norm CUDA kernel
(paddle/phi/kernels/gpu/layer_norm_kernel.cu): one VMEM-resident pass
computes mean/var and the normalized-scaled output per row tile, fp32
accumulation, bf16 in/out. Backward is a custom VJP over the jnp reference
(XLA fuses it well); the fwd kernel is the HBM-bandwidth win.
"""
import functools

import jax
import jax.numpy as jnp

from ._fallback import kernel_fallback

__all__ = ["fused_layer_norm", "fused_rms_norm",
           "fused_layer_norm_op", "fused_rms_norm_op"]


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # [rows, H]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(var + eps)
                  * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_ref(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) \
        * w.astype(x.dtype) + b.astype(x.dtype)


def _rms_ref(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), -1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _rows_block(n_rows, h, dtype):
    # target ~512KB of VMEM per input tile
    bytes_per = jnp.dtype(dtype).itemsize
    rows = max(8, min(n_rows, (512 * 1024) // max(h * bytes_per, 1)))
    while n_rows % rows:
        rows -= 1
    return rows


def _ln_fwd_impl(x, weight, bias, eps=1e-5):
    from jax.experimental import pallas as pl

    h = x.shape[-1]
    flat = x.reshape(-1, h)
    n = flat.shape[0]
    if h % 128 or n < 8:
        return _ln_ref(x, weight, bias, eps)
    rows = _rows_block(n, h, x.dtype)
    try:
        out = pl.pallas_call(
            functools.partial(_ln_kernel, eps=eps),
            grid=(n // rows,),
            in_specs=[
                pl.BlockSpec((rows, h), lambda i: (i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
            interpret=jax.default_backend() == "cpu",
        )(flat, weight, bias)
        return out.reshape(x.shape)
    except Exception as e:
        kernel_fallback("fused_layer_norm", e)
        return _ln_ref(x, weight, bias, eps)


def _ln_fwd(x, weight, bias, eps):
    return _ln_fwd_impl(x, weight, bias, eps), (x, weight, bias)


def _ln_bwd(res, g, eps):
    x, weight, bias = res
    _, vjp = jax.vjp(lambda x, w, b: _ln_ref(x, w, b, eps), x, weight, bias)
    return vjp(g)


def _rms_fwd_impl(x, weight, eps=1e-6):
    from jax.experimental import pallas as pl

    h = x.shape[-1]
    flat = x.reshape(-1, h)
    n = flat.shape[0]
    if h % 128 or n < 8:
        return _rms_ref(x, weight, eps)
    rows = _rows_block(n, h, x.dtype)
    try:
        out = pl.pallas_call(
            functools.partial(_rms_kernel, eps=eps),
            grid=(n // rows,),
            in_specs=[
                pl.BlockSpec((rows, h), lambda i: (i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
            interpret=jax.default_backend() == "cpu",
        )(flat, weight)
        return out.reshape(x.shape)
    except Exception as e:
        kernel_fallback("fused_rms_norm", e)
        return _rms_ref(x, weight, eps)


def _rms_fwd(x, weight, eps):
    return _rms_fwd_impl(x, weight, eps), (x, weight)


def _rms_bwd(res, g, eps):
    x, weight = res
    _, vjp = jax.vjp(lambda x, w: _rms_ref(x, w, eps), x, weight)
    return vjp(g)


# Registered through the PUBLIC custom-op path (utils.cpp_extension) — the
# in-tree proof that register_op carries a real Pallas kernel: these become
# paddle-level ops at custom_ops.fused_layer_norm / fused_rms_norm, while
# the module-level names keep their jax-level (array in/out) signatures
# for use inside jitted model code.
from ..utils.cpp_extension import register_op  # noqa: E402

fused_layer_norm_op = register_op(
    "fused_layer_norm", _ln_fwd_impl, vjp=_ln_bwd, fwd=_ln_fwd,
    static_argnames=("eps",), override=True,  # module reload-safe
    doc="Fused Pallas LayerNorm (fp32 accumulation, bf16 in/out)")
fused_rms_norm_op = register_op(
    "fused_rms_norm", _rms_fwd_impl, vjp=_rms_bwd, fwd=_rms_fwd,
    static_argnames=("eps",), override=True,
    doc="Fused Pallas RMSNorm (fp32 accumulation, bf16 in/out)")

fused_layer_norm = fused_layer_norm_op.raw
fused_rms_norm = fused_rms_norm_op.raw
