"""Weight-only int4 matmul for decode (W4A16).

Decode reads every weight byte each step; int4 halves that traffic vs
int8 (a8w8) and quarters it vs bf16 — the HBM roofline moves up
accordingly (bench.decode_roofline_tok_s). Storage: per-out-channel
symmetric int4 (q in [-7, 7], scale = amax/7), two nibbles packed per
int8 byte along the IN dim with a +8 offset (nibble value 1..15).

The Pallas kernel unpacks nibbles in VMEM (VPU int ops) and feeds the
MXU a bf16 tile — the dequantized weight never exists in HBM. The jnp
reference path computes the identical math (used on CPU and as the
fallback, and to verify the kernel bit-for-bit in interpret mode).

Reference counterpart: weight-only quant epilogues in
paddle/phi/kernels fused-matmul int8 paths — the int4 variant is the
TPU-side extension of the same bandwidth story.
"""
import functools

import jax
import jax.numpy as jnp

from ._fallback import kernel_fallback

__all__ = ["quantize_w4", "w4_matmul"]


def quantize_w4(w):
    """w [in, out] float -> (packed [ceil(in/2), out] int8 nibbles,
    scale [out] f32). Odd `in` is zero-padded (nibble 8 == value 0).
    Quantization itself is the shared recipe (quantization.quantize_weight
    with bits=4); only the nibble packing lives here."""
    from ..quantization import quantize_weight
    w = jnp.asarray(w)
    K, N = w.shape
    q, scale = quantize_weight(w, axis=0, bits=4)
    q = (q.astype(jnp.int32) + 8).astype(jnp.uint8)    # 1..15
    if K % 2:
        q = jnp.concatenate([q, jnp.full((1, N), 8, jnp.uint8)], axis=0)
    lo, hi = q[0::2], q[1::2]                # even rows -> low nibble
    return (lo | (hi << 4)).astype(jnp.int8), \
        scale.reshape(-1).astype(jnp.float32)


def _unpack_w4(packed, K):
    """packed [K2, N] int8 -> dequant-ready int [K, N] in [-7, 7]."""
    p = packed.astype(jnp.int32) & 0xFF      # int8 -> raw byte
    lo = (p & 0xF) - 8
    hi = ((p >> 4) & 0xF) - 8
    K2, N = p.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * K2, N)[:K]


def _w4_ref(x, packed, scale, K):
    w = _unpack_w4(packed, K).astype(jnp.float32) * scale
    return (x.astype(jnp.float32) @ w).astype(x.dtype)


def _w4_kernel(x_ref, p_ref, s_ref, o_ref, *, K):
    x = x_ref[...].astype(jnp.float32)       # [S, K]
    w = _unpack_w4(p_ref[...], K)            # [K, Nt] int
    wf = w.astype(jnp.float32) * s_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.dot(
        x, wf, preferred_element_type=jnp.float32).astype(o_ref.dtype)


def w4_matmul(x, packed, scale, K, block_n=256):
    """x [..., K] @ int4-packed weight -> [..., N]; dequant happens
    per-tile in VMEM (Pallas), never in HBM. Falls back to the jnp
    reference off-TPU or when the shape doesn't tile."""
    from jax.experimental import pallas as pl

    lead = x.shape[:-1]
    xf = x.reshape(-1, K)
    S = xf.shape[0]
    K2, N = packed.shape
    if N % block_n or K % 2 or S > 4096:
        return _w4_ref(xf, packed, scale, K).reshape(*lead, N)
    try:
        out = pl.pallas_call(
            functools.partial(_w4_kernel, K=K),
            grid=(N // block_n,),
            in_specs=[
                pl.BlockSpec((S, K), lambda i: (0, 0)),
                pl.BlockSpec((K2, block_n), lambda i: (0, i)),
                pl.BlockSpec((block_n,), lambda i: (i,)),
            ],
            out_specs=pl.BlockSpec((S, block_n), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((S, N), x.dtype),
            interpret=jax.default_backend() == "cpu",
        )(xf, packed, scale)
        return out.reshape(*lead, N)
    except Exception as e:
        kernel_fallback("w4_matmul", e)
        return _w4_ref(xf, packed, scale, K).reshape(*lead, N)
