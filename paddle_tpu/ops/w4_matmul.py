"""Weight-only int4 matmul for decode (W4A16).

Decode reads every weight byte each step; int4 halves that traffic vs
int8 (a8w8) and quarters it vs bf16 — the HBM roofline moves up
accordingly (bench.decode_roofline_tok_s). Storage: per-out-channel
symmetric int4 (q in [-7, 7], scale = amax/7), two nibbles packed per
int8 byte along the IN dim with a +8 offset (nibble value 1..15).

The Pallas kernel unpacks nibbles in VMEM (VPU int ops) and feeds the
MXU a bf16 tile — the dequantized weight never exists in HBM. The jnp
reference path computes the identical math (used on CPU and as the
fallback, and to verify the kernel bit-for-bit in interpret mode).

Reference counterpart: weight-only quant epilogues in
paddle/phi/kernels fused-matmul int8 paths — the int4 variant is the
TPU-side extension of the same bandwidth story.
"""
import functools

import jax
import jax.numpy as jnp

from ._fallback import kernel_fallback

__all__ = ["quantize_w4", "w4_matmul"]


def quantize_w4(w):
    """w [in, out] float -> (packed [ceil(in/2), out] int8 nibbles,
    scale [out] f32). Odd `in` is zero-padded (nibble 8 == value 0).
    Quantization itself is the shared recipe (quantization.quantize_weight
    with bits=4); only the nibble packing lives here."""
    from ..quantization import quantize_weight
    w = jnp.asarray(w)
    K, N = w.shape
    q, scale = quantize_weight(w, axis=0, bits=4)
    q = (q.astype(jnp.int32) + 8).astype(jnp.uint8)    # 1..15
    if K % 2:
        q = jnp.concatenate([q, jnp.full((1, N), 8, jnp.uint8)], axis=0)
    lo, hi = q[0::2], q[1::2]                # even rows -> low nibble
    return (lo | (hi << 4)).astype(jnp.int8), \
        scale.reshape(-1).astype(jnp.float32)


def _unpack_w4(packed, K):
    """packed [K2, N] int8 -> dequant-ready int [K, N] in [-7, 7]."""
    p = packed.astype(jnp.int32) & 0xFF      # int8 -> raw byte
    lo = (p & 0xF) - 8
    hi = ((p >> 4) & 0xF) - 8
    K2, N = p.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * K2, N)[:K]


def _w4_ref(x, packed, scale, K):
    w = _unpack_w4(packed, K).astype(jnp.float32) * scale
    return (x.astype(jnp.float32) @ w).astype(x.dtype)


def _w4_kernel(x_ref, p_ref, s_ref, o_ref, *, K):
    x = x_ref[...].astype(jnp.float32)       # [S, K]
    w = _unpack_w4(p_ref[...], K)            # [K, Nt] int
    wf = w.astype(jnp.float32) * s_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.dot(
        x, wf, preferred_element_type=jnp.float32).astype(o_ref.dtype)


def w4_matmul(x, packed, scale, K, block_n=256, block_s=512):
    """x [..., K] @ int4-packed weight -> [..., N]; dequant happens
    per-tile in VMEM (Pallas), never in HBM.

    Every shape tiles: an unaligned N first tries a SMALLER block (the
    largest power-of-two divisor of N >= 64 — the decoder's head-major
    384s and 128s tile exactly, no copies) and only a genuinely odd N
    (vocab projections like 50257) pads up to the block — an int8
    weight copy that is still far cheaper than the old silent fallback,
    which materialized the ENTIRE dequantized f32 weight in HBM. S
    tiles over a grid dimension in `block_s` rows (long prefill rows no
    longer bail at S > 4096; the weight tile streams once per S tile).
    The jnp reference remains the correctness twin and the fallback for
    odd-K packings and kernel failures."""
    from jax.experimental import pallas as pl

    lead = x.shape[:-1]
    xf = x.reshape(-1, K)
    S = xf.shape[0]
    K2, N = packed.shape
    if K % 2:
        return _w4_ref(xf, packed, scale, K).reshape(*lead, N)
    try:
        if N % block_n:
            b = N & -N                   # largest pow2 divisor of N
            if b >= 64:
                block_n = min(b, block_n)
        Np = -(-N // block_n) * block_n
        pk, sc = packed, scale
        if Np != N:
            # zero-padded columns: nibble byte 0 dequantizes to -8 * a
            # zero scale = 0, and the columns are sliced off anyway
            pk = jnp.pad(packed, ((0, 0), (0, Np - N)))
            sc = jnp.pad(scale, (0, Np - N))
        bs = min(block_s, S)
        Sp = -(-S // bs) * bs
        xp = jnp.pad(xf, ((0, Sp - S), (0, 0))) if Sp != S else xf
        out = pl.pallas_call(
            functools.partial(_w4_kernel, K=K),
            grid=(Sp // bs, Np // block_n),
            in_specs=[
                pl.BlockSpec((bs, K), lambda i, j: (i, 0)),
                pl.BlockSpec((K2, block_n), lambda i, j: (0, j)),
                pl.BlockSpec((block_n,), lambda i, j: (j,)),
            ],
            out_specs=pl.BlockSpec((bs, block_n), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((Sp, Np), x.dtype),
            interpret=jax.default_backend() == "cpu",
        )(xp, pk, sc)
        return out[:S, :N].reshape(*lead, N)
    except Exception as e:
        kernel_fallback("w4_matmul", e)
        return _w4_ref(xf, packed, scale, K).reshape(*lead, N)
