"""Flash attention for TPU.

Replaces the reference's fused_attention CUDA op
(paddle/fluid/operators/fused/fused_attention_op.cu) with a Pallas kernel
tiled for MXU/VMEM. The jnp fallback keeps CPU tests and odd shapes working;
`flash_attention` dispatches.

Layout convention is paddle's: [batch, seq, heads, head_dim].
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ._fallback import kernel_fallback

__all__ = ["flash_attention", "flash_attention_available", "mha_reference"]


def _on_tpu():
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def flash_attention_available(query, attn_mask, dropout_p):
    if attn_mask is not None or dropout_p:
        return False
    shape = query.shape if not isinstance(query, Tensor) else query.shape
    L, D = shape[1], shape[3]
    return _on_tpu() and L % 128 == 0 and D in (64, 128, 256)


def mha_reference(q, k, v, causal=False, scale=None, attn_mask=None):
    """jnp reference (fp32 softmax) — [B,L,H,D] in/out. Supports GQA
    (fewer K/V heads: Hq % Hkv == 0) and an additive attn_mask broadcastable
    to [B, H, Lq, Lk] (bool masks: True = keep)."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    logits = (qh @ jnp.swapaxes(kh, -1, -2)).astype(jnp.float32) * scale
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    if causal:
        L, S = logits.shape[-2], logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((L, S), bool)), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.swapaxes(probs @ vh, 1, 2)


def _fold_gqa(qh, hkv):
    """(B, Hq, Lq, D) -> (B, Hkv, G*Lq, D): query heads sharing a KV head are
    stacked along the row axis (rows are independent in attention). Head
    ordering is h = h_kv * G + g, matching repeat-interleave GQA."""
    B, Hq, Lq, D = qh.shape
    g = Hq // hkv
    return qh.reshape(B, hkv, g * Lq, D), Lq


def _unfold_gqa(out, hq, lq):
    B, hkv, gl, D = out.shape
    return out.reshape(B, hq, lq, D)


# ---------------------------------------------------------------------------
# Pallas kernel: online-softmax flash attention (fwd) with custom VJP (bwd
# recomputes probabilities blockwise — standard flash backward).
# ---------------------------------------------------------------------------
_BLOCK_Q = 256
_BLOCK_K = 256


def _block(L, pref):
    """Largest of (pref, 128) dividing L, else L itself — the grids below use
    exact tiling (L // block), so the block MUST divide L."""
    for cand in (pref, 128):
        if L % cand == 0:
            return cand
    return L


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k,
                      seq_k, seq_q_real=None):
    from jax.experimental import pallas as pl

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, d]
    bq, d = q.shape
    q_idx = pl.program_id(2)
    # with GQA the group is folded into the row axis; causal positions are
    # modulo the real sequence length (blocks never straddle heads: bq | Lq)
    row0 = q_idx * bq if seq_q_real is None else (q_idx * bq) % seq_q_real

    m = jnp.full((bq, 1), -1e30, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    n_k = seq_k // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [bq, bk]
        if causal:
            q_pos = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + p @ v
        return m_new, l_new, acc_new

    if causal:
        # only k-blocks at or before this q-block's end participate
        q_end = row0 + bq
        n_live = jnp.minimum((q_end + block_k - 1) // block_k, n_k)
        m, l, acc = jax.lax.fori_loop(0, n_live, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_k, body, (m, l, acc))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                          block_k, seq_k, seq_q_real=None):
    """Forward that also writes logsumexp rows (for the Pallas backward)."""
    from jax.experimental import pallas as pl

    q = q_ref[0, 0].astype(jnp.float32) * scale
    bq, d = q.shape
    q_idx = pl.program_id(2)
    row0 = q_idx * bq if seq_q_real is None else (q_idx * bq) % seq_q_real
    m = jnp.full((bq, 1), -1e30, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    n_k = seq_k // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))
        if causal:
            q_pos = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + p @ v
        return m_new, l_new, acc_new

    if causal:
        q_end = row0 + bq
        n_live = jnp.minimum((q_end + block_k - 1) // block_k, n_k)
        m, l, acc = jax.lax.fori_loop(0, n_live, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_k, body, (m, l, acc))
    lsafe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / lsafe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(lsafe)          # (bq, 1) trailing unit lane


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, causal, block_k, seq_k,
                         seq_q_real=None):
    """dQ = sum_k dS @ K with dS = P * (dP - delta) * scale, P recomputed
    blockwise from the saved logsumexp (standard flash backward)."""
    from jax.experimental import pallas as pl

    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)          # (bq, 1)
    delta = delta_ref[0, 0].astype(jnp.float32)      # (bq, 1)
    bq, d = q.shape
    q_idx = pl.program_id(2)
    row0 = q_idx * bq if seq_q_real is None else (q_idx * bq) % seq_q_real
    n_k = seq_k // block_k
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(i, dq):
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
        if causal:
            q_pos = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta) * scale
        return dq + ds @ k

    if causal:
        q_end = row0 + bq
        n_live = jnp.minimum((q_end + block_k - 1) // block_k, n_k)
        dq = jax.lax.fori_loop(0, n_live, body, dq)
    else:
        dq = jax.lax.fori_loop(0, n_k, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, causal, block_q, seq_q,
                          seq_q_real=None):
    """dK/dV for one k block, looping over q blocks."""
    from jax.experimental import pallas as pl

    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    bk, d = k.shape
    k_idx = pl.program_id(2)
    n_q = seq_q // block_q
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [bq, bk]
        if causal:
            r0 = i * block_q if seq_q_real is None else (i * block_q) % seq_q_real
            q_pos = r0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = k_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)
        dv_new = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())))
        return dk_new, dv_new

    if causal and seq_q_real is None:
        # only q blocks at or after this k block's start participate
        q_start = (k_idx * bk) // block_q
        dk, dv = jax.lax.fori_loop(q_start, n_q, body, (dk, dv))
    else:
        # folded GQA rows repeat positions; masking handles the skips
        dk, dv = jax.lax.fori_loop(0, n_q, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_fwd_lse_impl(q, k, v, causal, scale, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, Lq, Hq, D = q.shape
    Lk = k.shape[1]
    Hkv = k.shape[2]
    bq = _block(Lq, _BLOCK_Q)
    bk = _block(Lk, _BLOCK_K)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    seq_q_real = None
    if Hq != Hkv:
        qh, seq_q_real = _fold_gqa(qh, Hkv)
    H = Hkv
    Lq_f = qh.shape[2]
    grid = (B, H, Lq_f // bq)
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_lse_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=Lk, seq_q_real=seq_q_real),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq_f, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq_f, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    if seq_q_real is not None:
        out = _unfold_gqa(out, Hq, Lq)
    return jnp.swapaxes(out, 1, 2), lse


def _flash_bwd_impl(q, k, v, out, lse, g, causal, scale, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, Lq, Hq, D = q.shape
    Lk = k.shape[1]
    Hkv = k.shape[2]
    bq = _block(Lq, _BLOCK_Q)
    bk = _block(Lk, _BLOCK_K)
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    doh = jnp.swapaxes(g, 1, 2)
    oh = jnp.swapaxes(out, 1, 2)
    seq_q_real = None
    if Hq != Hkv:
        qh, seq_q_real = _fold_gqa(qh, Hkv)
        doh, _ = _fold_gqa(doh, Hkv)
        oh, _ = _fold_gqa(oh, Hkv)
        # lse from the folded forward is already (B, Hkv, G*Lq, 1)
    H = Hkv
    Lq_f = qh.shape[2]
    delta = jnp.sum(doh.astype(jnp.float32) * oh.astype(jnp.float32),
                    axis=-1, keepdims=True)           # (B, H, Lq_f, 1)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=Lk, seq_q_real=seq_q_real),
        grid=(B, H, Lq_f // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Lq_f, D), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, seq_q=Lq_f, seq_q_real=seq_q_real),
        grid=(B, H, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, Lq_f, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, Lq_f, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lq_f, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lq_f, 1), lambda b, h, j: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Lk, D), v.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)
    if seq_q_real is not None:
        dq = _unfold_gqa(dq, Hq, Lq)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    return _flash_fwd(q, k, v, causal, scale)


def _flash_fwd_impl(q, k, v, causal, scale, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, Lq, Hq, D = q.shape
    Lk = k.shape[1]
    Hkv = k.shape[2]
    bq = _block(Lq, _BLOCK_Q)
    bk = _block(Lk, _BLOCK_K)
    # [B,L,H,D] -> [B,H,L,D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    seq_q_real = None
    if Hq != Hkv:
        qh, lq_real = _fold_gqa(qh, Hkv)
        seq_q_real = lq_real
    H = Hkv
    Lq_f = qh.shape[2]

    grid = (B, H, Lq_f // bq)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=Lk, seq_q_real=seq_q_real),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Lq_f, D), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    if seq_q_real is not None:
        out = _unfold_gqa(out, Hq, Lq)
    return jnp.swapaxes(out, 1, 2)


def _flash_fwd(q, k, v, causal, scale):
    try:
        return _flash_fwd_impl(q, k, v, causal, scale)
    except Exception as e:
        kernel_fallback("flash_attention_fwd", e)
        return mha_reference(q, k, v, causal=causal, scale=scale)


def _flash_fwd_vjp(q, k, v, causal, scale):
    try:
        out, lse = _flash_fwd_lse_impl(q, k, v, causal, scale)
        return out, (q, k, v, out, lse)
    except Exception as e:
        kernel_fallback("flash_attention_fwd_lse", e)
        out = mha_reference(q, k, v, causal=causal, scale=scale)
        return out, (q, k, v, out, None)


def _flash_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        try:
            return _flash_bwd_impl(q, k, v, out, lse, g, causal, scale)
        except Exception as e:
            kernel_fallback("flash_attention_bwd", e)
    # fallback: XLA vjp of the reference (materializes L x L probs)
    def f(q, k, v):
        return mha_reference(q, k, v, causal=causal, scale=scale)
    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_vjp, _flash_bwd)


def flash_attention(query, key, value, causal=False, scale=None,
                    attn_mask=None):
    """Public fused attention — Tensor in/out, [B,L,H,D]. Supports GQA
    (key/value with fewer heads; folded into the same kernels) and additive
    or boolean attn_mask (masked path runs the XLA reference — the mask is
    O(L^2) HBM anyway, so the flash win is gone)."""
    sc = scale if scale is not None else 1.0 / np.sqrt(query.shape[-1])
    hq = query.shape[2]
    hkv = key.shape[2]
    if hq % hkv != 0:
        raise ValueError(f"query heads ({hq}) must be a multiple of "
                         f"key/value heads ({hkv}) for GQA")
    if attn_mask is not None:
        fn = lambda q, k, v, m: mha_reference(q, k, v, causal=causal,
                                              scale=sc, attn_mask=m)
        if isinstance(query, Tensor):
            return apply_op(fn, query, key, value, attn_mask)
        return fn(query, key, value, attn_mask)
    if isinstance(query, Tensor):
        return apply_op(lambda q, k, v: _flash(q, k, v, causal, sc), query, key, value)
    return _flash(query, key, value, causal, sc)
