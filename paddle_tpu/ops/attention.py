"""Flash attention for TPU.

Replaces the reference's fused_attention CUDA op
(paddle/fluid/operators/fused/fused_attention_op.cu) with a Pallas kernel
tiled for MXU/VMEM. The jnp fallback keeps CPU tests and odd shapes working;
`flash_attention` dispatches.

Layout convention is paddle's: [batch, seq, heads, head_dim].

Feature set (all with the fused online-softmax kernel, fwd + bwd):
- causal masking (block-skip on the k loop)
- GQA (fewer K/V heads; query heads folded into the row axis)
- additive or boolean attention masks: padding-style masks ([B, Lk]-ish)
  stream as an O(L) bias; general masks broadcastable to [B, H, Lq, Lk]
  stream blockwise from HBM (the mask is O(L^2) wherever it lives, but the
  probability matrix is never materialized and the matmuls stay fused)
- dropout on the attention probabilities, computed inside the kernel from a
  counter-based hash of (seed, batch, kv-head, row, col) — the backward pass
  regenerates the identical mask, nothing is stored
- sequence lengths that are not multiples of 128 (padded + masked here, so
  callers always hit the kernel)
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ._fallback import kernel_fallback

__all__ = ["flash_attention", "flash_attention_available", "mha_reference"]

import os

# Tile sizes for the flash kernel grid; overridable via env or
# incubate.autotune.tune_flash_attention (multiples of 128 — the MXU/VREG
# lane width). 512x512 measured 4% faster than 256x256 on GPT-1.3B
# bs4/seq1024 (v5e); sweeps clamp to the actual sequence length.


def _env_block(name, default):
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    # normalize to a positive multiple of 128 so _block's descending walk
    # always reaches the 128 fallback
    return max(128, (v // 128) * 128)


_BLOCK_Q = _env_block("PADDLE_TPU_FLASH_BLOCK_Q", 512)
_BLOCK_K = _env_block("PADDLE_TPU_FLASH_BLOCK_K", 512)
_NEG = -1e30


def _on_tpu():
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def flash_attention_available(query, attn_mask, dropout_p):
    """Masks and dropout now run inside the kernel; the only remaining gate
    is the head_dim tiling and the backend."""
    D = query.shape[3]
    return _on_tpu() and D in (64, 128, 256)


# ---------------------------------------------------------------------------
# Deterministic dropout hash — identical math inside the Pallas kernels, the
# jnp reference, and (for tests) numpy. lowbias32 finalizer on a position
# counter; keep iff hash >= rate * 2^32.
# ---------------------------------------------------------------------------
_K_ROW = 0x9E3779B1
_K_COL = 0x85EBCA77
_K_B = 0xC2B2AE3D
_K_H = 0x27D4EB2F


def _hash32(x):
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _drop_salt(seed_u32, b, h):
    return _hash32(seed_u32
                   ^ (jnp.uint32(b) * jnp.uint32(_K_B))
                   ^ (jnp.uint32(h) * jnp.uint32(_K_H)))


def _rate_thresh(rate):
    return jnp.uint32(min(int(float(rate) * 4294967296.0), 4294967295))


def _keep_tile(salt, rows, cols, rate):
    """Boolean keep-mask [len(rows), len(cols)] from absolute positions."""
    r = rows.astype(jnp.uint32)[:, None] * jnp.uint32(_K_ROW)
    c = cols.astype(jnp.uint32)[None, :] * jnp.uint32(_K_COL)
    return _hash32(r ^ c ^ salt) >= _rate_thresh(rate)


def mha_reference(q, k, v, causal=False, scale=None, attn_mask=None,
                  dropout_rate=0.0, dropout_seed=0):
    """jnp reference (fp32 softmax) — [B,L,H,D] in/out. Supports GQA
    (fewer K/V heads: Hq % Hkv == 0), an additive attn_mask broadcastable
    to [B, H, Lq, Lk] (bool masks: True = keep), and hash-based dropout that
    reproduces the Pallas kernel's pattern exactly (same seed ⇒ same mask)."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    logits = (qh @ jnp.swapaxes(kh, -1, -2)).astype(jnp.float32) * scale
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, _NEG)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    if causal:
        L, S = logits.shape[-2], logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((L, S), bool)), logits, _NEG)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate:
        B, H, Lq, Lk = probs.shape
        g = hq // hkv
        lq_real = Lq
        seed_u = jnp.asarray(dropout_seed).astype(jnp.int32).astype(jnp.uint32)
        # kernel coordinates: (b, h_kv, folded_row = (h % g) * Lq + row, col)
        bidx = jnp.arange(B, dtype=jnp.uint32)[:, None, None, None]
        hidx = jnp.arange(H, dtype=jnp.uint32)[None, :, None, None]
        rows = jnp.arange(Lq, dtype=jnp.uint32)[None, None, :, None]
        cols = jnp.arange(Lk, dtype=jnp.uint32)[None, None, None, :]
        hkv_idx = hidx // jnp.uint32(g)
        frow = (hidx % jnp.uint32(g)) * jnp.uint32(lq_real) + rows
        salt = _hash32(seed_u
                       ^ (bidx * jnp.uint32(_K_B))
                       ^ (hkv_idx * jnp.uint32(_K_H)))
        keep = _hash32(frow * jnp.uint32(_K_ROW)
                       ^ cols * jnp.uint32(_K_COL)
                       ^ salt) >= _rate_thresh(dropout_rate)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    probs = probs.astype(q.dtype)
    return jnp.swapaxes(probs @ vh, 1, 2)


def _block(L, pref):
    """Largest multiple-of-128 tile <= pref dividing L, else L itself — the
    grids below use exact tiling (L // block), so the block MUST divide L.
    Descending multiples (not just {pref, 128}) so e.g. L=768 still tiles
    at 256 when pref is 512."""
    cand = pref
    while cand >= 128:
        if L % cand == 0:
            return cand
        cand -= 128
    return L


def _fold_gqa(qh, hkv):
    """(B, Hq, Lq, D) -> (B, Hkv, G*Lq, D): query heads sharing a KV head are
    stacked along the row axis (rows are independent in attention). Head
    ordering is h = h_kv * G + g, matching repeat-interleave GQA."""
    B, Hq, Lq, D = qh.shape
    g = Hq // hkv
    return qh.reshape(B, hkv, g * Lq, D), Lq


def _unfold_gqa(out, hq, lq):
    B, hkv, gl, D = out.shape
    return out.reshape(B, hq, lq, D)


# ---------------------------------------------------------------------------
# Pallas kernels: online-softmax flash attention (fwd + lse) with custom VJP
# (bwd recomputes probabilities blockwise — standard flash backward). All
# three kernels share the same optional-ref convention: after q/k/v (and
# do/lse/delta for the backward) come, in order and only when enabled:
#   kvb_ref  — (1, Lk) f32 additive bias broadcast over rows (padding masks)
#   fb_ref   — blockwise tile of a full additive bias [Bm, Hm, fb_rows, Lk]
#   seed_ref — (1, 1) f32 dropout seed
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, scale, causal, block_k, seq_k, seq_q_real,
                has_kvb, has_fb, fb_rows, rate):
    from jax.experimental import pallas as pl

    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    rest = refs[3:]
    kvb_ref = rest.pop(0) if has_kvb else None
    fb_ref = rest.pop(0) if has_fb else None
    seed_ref = rest.pop(0) if rate else None
    o_ref, lse_ref = rest

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, d]
    bq, d = q.shape
    q_idx = pl.program_id(2)
    row0_f = q_idx * bq                               # absolute folded row
    # with GQA the group is folded into the row axis; causal positions are
    # modulo the real sequence length (blocks never straddle heads: bq | Lq)
    row0 = row0_f if seq_q_real is None else row0_f % seq_q_real
    if rate:
        seed_u = seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
        salt = _drop_salt(seed_u, pl.program_id(0), pl.program_id(1))

    m = jnp.full((bq, 1), _NEG, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    n_k = seq_k // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [bq, bk]
        if has_kvb:
            s = s + kvb_ref[0, 0, pl.dslice(i * block_k, block_k)][None, :]
        if has_fb:
            s = s + fb_ref[0, 0, :, pl.dslice(i * block_k, block_k)]
        if causal:
            q_pos = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if rate:
            rows = row0_f + jnp.arange(bq, dtype=jnp.int32)
            cols = i * block_k + jnp.arange(block_k, dtype=jnp.int32)
            keep = _keep_tile(salt, rows, cols, rate)
            p_use = p * keep.astype(jnp.float32) / (1.0 - rate)
        else:
            p_use = p
        acc_new = acc * corr + p_use @ v
        return m_new, l_new, acc_new

    if causal:
        # only k-blocks at or before this q-block's end participate
        q_end = row0 + bq
        n_live = jnp.minimum((q_end + block_k - 1) // block_k, n_k)
        m, l, acc = jax.lax.fori_loop(0, n_live, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_k, body, (m, l, acc))
    lsafe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / lsafe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(lsafe)          # (bq, 1) trailing unit lane


def _bwd_dq_kernel(*refs, scale, causal, block_k, seq_k, seq_q_real,
                   has_kvb, has_fb, fb_rows, rate):
    """dQ = sum_k dS @ K with dS = P * (D·dP - delta) * scale, P recomputed
    blockwise from the saved logsumexp (standard flash backward; D is the
    regenerated dropout keep/(1-rate) factor)."""
    from jax.experimental import pallas as pl

    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    rest = refs[6:]
    kvb_ref = rest.pop(0) if has_kvb else None
    fb_ref = rest.pop(0) if has_fb else None
    seed_ref = rest.pop(0) if rate else None
    dq_ref, = rest

    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)          # (bq, 1)
    delta = delta_ref[0, 0].astype(jnp.float32)      # (bq, 1)
    bq, d = q.shape
    q_idx = pl.program_id(2)
    row0_f = q_idx * bq
    row0 = row0_f if seq_q_real is None else row0_f % seq_q_real
    if rate:
        seed_u = seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
        salt = _drop_salt(seed_u, pl.program_id(0), pl.program_id(1))
    n_k = seq_k // block_k
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(i, dq):
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
        if has_kvb:
            s = s + kvb_ref[0, 0, pl.dslice(i * block_k, block_k)][None, :]
        if has_fb:
            s = s + fb_ref[0, 0, :, pl.dslice(i * block_k, block_k)]
        if causal:
            q_pos = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        if rate:
            rows = row0_f + jnp.arange(bq, dtype=jnp.int32)
            cols = i * block_k + jnp.arange(block_k, dtype=jnp.int32)
            keep = _keep_tile(salt, rows, cols, rate)
            dp = dp * keep.astype(jnp.float32) / (1.0 - rate)
        ds = p * (dp - delta) * scale
        return dq + ds @ k

    if causal:
        q_end = row0 + bq
        n_live = jnp.minimum((q_end + block_k - 1) // block_k, n_k)
        dq = jax.lax.fori_loop(0, n_live, body, dq)
    else:
        dq = jax.lax.fori_loop(0, n_k, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, seq_q, seq_q_real,
                    has_kvb, has_fb, fb_rows, rate):
    """dK/dV for one k block, looping over q blocks."""
    from jax.experimental import pallas as pl

    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    rest = refs[6:]
    kvb_ref = rest.pop(0) if has_kvb else None
    fb_ref = rest.pop(0) if has_fb else None
    seed_ref = rest.pop(0) if rate else None
    dk_ref, dv_ref = rest

    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    bk, d = k.shape
    k_idx = pl.program_id(2)
    if rate:
        seed_u = seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
        salt = _drop_salt(seed_u, pl.program_id(0), pl.program_id(1))
    n_q = seq_q // block_q
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [bq, bk]
        if has_kvb:
            s = s + kvb_ref[0, 0, pl.dslice(k_idx * bk, bk)][None, :]
        if has_fb:
            r0m = (i * block_q) % fb_rows
            s = s + fb_ref[0, 0, pl.dslice(r0m, block_q), :]
        if causal:
            r0 = i * block_q if seq_q_real is None else (i * block_q) % seq_q_real
            q_pos = r0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = k_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        if rate:
            rows = i * block_q + jnp.arange(block_q, dtype=jnp.int32)
            cols = k_idx * bk + jnp.arange(bk, dtype=jnp.int32)
            keep = _keep_tile(salt, rows, cols, rate).astype(jnp.float32)
            scale_keep = keep / (1.0 - rate)
            p_drop = p * scale_keep
            dp = dp * scale_keep
        else:
            p_drop = p
        dv_new = dv + jax.lax.dot_general(p_drop, do, (((0,), (0,)), ((), ())))
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())))
        return dk_new, dv_new

    if causal and seq_q_real is None:
        # only q blocks at or after this k block's start participate
        q_start = (k_idx * bk) // block_q
        dk, dv = jax.lax.fori_loop(q_start, n_q, body, (dk, dv))
    else:
        # folded GQA rows repeat positions; masking handles the skips
        dk, dv = jax.lax.fori_loop(0, n_q, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Impl wrappers: fold GQA, normalize biases to block specs, run pallas_call.
# cfg = (causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h)
# ---------------------------------------------------------------------------


def _bias_specs(cfg, B, H, bq, Lk, fb_rows, kvb, fb, seed, for_dkv=False, bk=None):
    """Extra in_specs + inputs for (kvb?, fb?, seed?) in kernel order."""
    from jax.experimental import pallas as pl

    causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h = cfg
    specs, args = [], []
    if has_kvb:
        # [Bm, 1, Lk]: the unit middle dim keeps the last-two block dims
        # (1, Lk) equal to the array dims — TPU tiling requirement
        specs.append(pl.BlockSpec(
            (1, 1, Lk), lambda b, h, i, _kb=kvb_b: (b if _kb else 0, 0, 0)))
        args.append(kvb)
    if has_fb:
        n_rb = fb_rows // bq
        if for_dkv:
            specs.append(pl.BlockSpec(
                (1, 1, fb_rows, bk),
                lambda b, h, j, _fb=fb_b, _fh=fb_h: (b if _fb else 0, h if _fh else 0, 0, j)))
        else:
            specs.append(pl.BlockSpec(
                (1, 1, bq, Lk),
                lambda b, h, i, _fb=fb_b, _fh=fb_h, _n=n_rb: (b if _fb else 0, h if _fh else 0, i % _n, 0)))
        args.append(fb)
    if rate:
        specs.append(pl.BlockSpec((1, 1), lambda b, h, i: (0, 0)))
        args.append(seed)
    return specs, args


def _fwd_lse_impl(q, k, v, kvb, fb, seed, cfg, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h = cfg
    B, Lq, Hq, D = q.shape
    Lk, Hkv = k.shape[1], k.shape[2]
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    seq_q_real = None
    if Hq != Hkv:
        qh, seq_q_real = _fold_gqa(qh, Hkv)
    H = Hkv
    Lq_f = qh.shape[2]
    # blocks must never straddle a folded head boundary: bq | real Lq
    bq = _block(Lq if seq_q_real is None else seq_q_real, _BLOCK_Q)
    bk = _block(Lk, _BLOCK_K)
    fb_rows = fb.shape[2] if has_fb else Lq_f
    if has_kvb and kvb.ndim == 2:
        kvb = kvb[:, None, :]
    grid = (B, H, Lq_f // bq)
    extra_specs, extra_args = _bias_specs(cfg, B, H, bq, Lk, fb_rows, kvb, fb, seed)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=Lk, seq_q_real=seq_q_real,
                          has_kvb=has_kvb, has_fb=has_fb, fb_rows=fb_rows,
                          rate=rate),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
        ] + extra_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq_f, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Lq_f, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh, *extra_args)
    if seq_q_real is not None:
        out = _unfold_gqa(out, Hq, Lq)
    return jnp.swapaxes(out, 1, 2), lse


def _bwd_impl(q, k, v, lse, g, out, kvb, fb, seed, cfg, interpret=None):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h = cfg
    B, Lq, Hq, D = q.shape
    Lk, Hkv = k.shape[1], k.shape[2]
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    doh = jnp.swapaxes(g, 1, 2)
    oh = jnp.swapaxes(out, 1, 2)
    seq_q_real = None
    if Hq != Hkv:
        qh, seq_q_real = _fold_gqa(qh, Hkv)
        doh, _ = _fold_gqa(doh, Hkv)
        oh, _ = _fold_gqa(oh, Hkv)
        # lse from the folded forward is already (B, Hkv, G*Lq, 1)
    H = Hkv
    Lq_f = qh.shape[2]
    bq = _block(Lq if seq_q_real is None else seq_q_real, _BLOCK_Q)
    bk = _block(Lk, _BLOCK_K)
    fb_rows = fb.shape[2] if has_fb else Lq_f
    if has_kvb and kvb.ndim == 2:
        kvb = kvb[:, None, :]
    delta = jnp.sum(doh.astype(jnp.float32) * oh.astype(jnp.float32),
                    axis=-1, keepdims=True)           # (B, H, Lq_f, 1)

    extra_specs, extra_args = _bias_specs(cfg, B, H, bq, Lk, fb_rows, kvb, fb, seed)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=Lk, seq_q_real=seq_q_real,
                          has_kvb=has_kvb, has_fb=has_fb, fb_rows=fb_rows,
                          rate=rate),
        grid=(B, H, Lq_f // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ] + extra_specs,
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Lq_f, D), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta, *extra_args)

    extra_specs, extra_args = _bias_specs(cfg, B, H, bq, Lk, fb_rows, kvb, fb, seed,
                                          for_dkv=True, bk=bk)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, seq_q=Lq_f, seq_q_real=seq_q_real,
                          has_kvb=has_kvb, has_fb=has_fb, fb_rows=fb_rows,
                          rate=rate),
        grid=(B, H, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, Lq_f, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, Lq_f, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lq_f, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Lq_f, 1), lambda b, h, j: (b, h, 0, 0)),
        ] + extra_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Lk, D), v.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta, *extra_args)
    if seq_q_real is not None:
        dq = _unfold_gqa(dq, Hq, Lq)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


# ---------------------------------------------------------------------------
# custom_vjp core. Extras (kvb, fb, seed) are always passed (dummy (1, 1)
# zeros when unused — cfg flags gate both the kernels and the specs), so one
# function covers every feature combination without None-pytree contortions.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_core(cfg, q, k, v, kvb, fb, seed):
    out, _ = _flash_core_fwd(cfg, q, k, v, kvb, fb, seed)
    return out


def _ref_with_extras(cfg, q, k, v, kvb, fb, seed):
    causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h = cfg
    mask = None
    if has_kvb:
        mask = kvb[:, None, None, :]
    if has_fb:
        m = fb  # [Bm, Hm', rows, Lk]
        if fb_h and q.shape[2] != k.shape[2]:
            # pre-folded rows: unfold back to [Bm, Hq, Lq, Lk]
            g = q.shape[2] // k.shape[2]
            m = fb.reshape(fb.shape[0], fb.shape[1] * g, fb.shape[2] // g, fb.shape[3])
        mask = m if mask is None else mask + m
    return mha_reference(q, k, v, causal=causal, scale=scale, attn_mask=mask,
                         dropout_rate=rate, dropout_seed=seed.reshape(-1)[0])


def _flash_core_fwd(cfg, q, k, v, kvb, fb, seed):
    try:
        out, lse = _fwd_lse_impl(q, k, v, kvb, fb, seed, cfg)
        return out, (q, k, v, kvb, fb, seed, lse, out)
    except Exception as e:
        kernel_fallback("flash_attention_fwd", e)
        out = _ref_with_extras(cfg, q, k, v, kvb, fb, seed)
        return out, (q, k, v, kvb, fb, seed, None, out)


def _flash_core_bwd(cfg, res, g):
    q, k, v, kvb, fb, seed, lse, out = res
    zeros = (jnp.zeros_like(kvb), jnp.zeros_like(fb), jnp.zeros_like(seed))
    if lse is not None:
        try:
            dq, dk, dv = _bwd_impl(q, k, v, lse, g, out, kvb, fb, seed, cfg)
            return (dq, dk, dv) + zeros
        except Exception as e:
            kernel_fallback("flash_attention_bwd", e)
    # fallback: XLA vjp of the reference (materializes L x L probs)
    def f(q, k, v):
        return _ref_with_extras(cfg, q, k, v, kvb, fb, seed)
    _, vjp = jax.vjp(f, q, k, v)
    return tuple(vjp(g)) + zeros


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)

_DUMMY_CFG_TAIL = (False, False, False, False, False)


def _plain_cfg(causal, scale):
    return (bool(causal), float(scale), 0.0) + _DUMMY_CFG_TAIL


def _dummy():
    return jnp.zeros((1, 1), jnp.float32)


def _flash(q, k, v, causal, scale):
    """Mask-free, dropout-free entry (ulysses + back-compat)."""
    d = _dummy()
    return _flash_core(_plain_cfg(causal, scale), q, k, v, d, d, d)


def flash_raw_or_reference(q, k, v, causal=True, scale=None):
    """Raw-array dispatch for code already inside jit/shard_map (stacked
    GPT blocks, pipeline stages): the Pallas kernel when the backend and
    tiling allow, else the jnp reference — same numerics. Unlike the
    public flash_attention it does NOT pad: non-128-multiple sequence
    lengths would only fail at XLA compile (beyond the trace-time
    except), so they take the reference path instead."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if flash_attention_available(q, None, 0.0) \
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0:
        try:
            return _flash(q, k, v, causal, scale)
        except Exception as e:
            kernel_fallback("flash_raw", e)
    return mha_reference(q, k, v, causal=causal, scale=scale)


# -- back-compat impl wrappers (tests drive these in interpret mode) --------


def _flash_fwd_impl(q, k, v, causal, scale, interpret=None):
    d = _dummy()
    out, _ = _fwd_lse_impl(q, k, v, d, d, d, _plain_cfg(causal, scale),
                           interpret=interpret)
    return out


def _flash_fwd_lse_impl(q, k, v, causal, scale, interpret=None):
    d = _dummy()
    return _fwd_lse_impl(q, k, v, d, d, d, _plain_cfg(causal, scale),
                         interpret=interpret)


def _flash_bwd_impl(q, k, v, out, lse, g, causal, scale, interpret=None):
    d = _dummy()
    return _bwd_impl(q, k, v, lse, g, out, d, d, d, _plain_cfg(causal, scale),
                     interpret=interpret)


# ---------------------------------------------------------------------------
# Dispatch: mask normalization, seq padding, seed plumbing.
# ---------------------------------------------------------------------------

_seed_counter = [0]


def _next_seed():
    """Per-call dropout seed. Eager calls draw from the paddle global RNG
    (deterministic after paddle.seed); under jit tracing this becomes a
    trace-time constant — pass `dropout_seed` explicitly per step to vary
    the pattern inside a compiled training step."""
    from ..framework import random as _random

    _seed_counter[0] += 1
    try:
        key = _random.next_key()
        return int(jax.random.randint(key, (), 0, 1 << 24))
    except Exception:
        return _seed_counter[0]


def _normalize_mask(attn_mask, B, Hq, Lq, Lk, dtype_neg=_NEG):
    """Split an arbitrary broadcastable mask into (kvb [Bm, Lk]) or
    (fb [Bm, Hm, Lq(m), Lk]) additive fp32 biases."""
    m = attn_mask
    if m.dtype == jnp.bool_:
        m = jnp.where(m, 0.0, dtype_neg).astype(jnp.float32)
    else:
        m = m.astype(jnp.float32)
    while m.ndim < 4:
        m = m[None]
    Bm, Hm, Lqm, Lkm = m.shape
    if Lkm == 1:
        m = jnp.broadcast_to(m, (Bm, Hm, Lqm, Lk))
    if Hm == 1 and Lqm == 1:
        return m.reshape(m.shape[0], m.shape[3]), None
    if Lqm == 1:
        m = jnp.broadcast_to(m, (Bm, Hm, Lq, m.shape[3]))
    return None, m


def flash_attention(query, key, value, causal=False, scale=None,
                    attn_mask=None, dropout_rate=0.0, dropout_seed=None):
    """Public fused attention — Tensor in/out, [B,L,H,D]. Supports GQA
    (key/value with fewer heads), additive or boolean attn_mask, and
    attention-probability dropout, all inside the Pallas kernel."""
    sc = scale if scale is not None else 1.0 / np.sqrt(query.shape[-1])
    B, Lq, Hq, D = query.shape
    Lk, Hkv = key.shape[1], key.shape[2]
    if Hq % Hkv != 0:
        raise ValueError(f"query heads ({Hq}) must be a multiple of "
                         f"key/value heads ({Hkv}) for GQA")
    rate = float(dropout_rate or 0.0)
    seed_val = dropout_seed if dropout_seed is not None else (
        _next_seed() if rate else 0)

    def run(q, k, v, *maybe_mask):
        m = maybe_mask[0] if maybe_mask else None
        lq, lk = q.shape[1], k.shape[1]
        pad_q = (-lq) % 128 if lq % 128 else 0
        pad_k = (-lk) % 128 if lk % 128 else 0
        kvb = fb = None
        if m is not None:
            kvb, fb = _normalize_mask(m, q.shape[0], q.shape[2], lq, lk)
        if pad_q or pad_k:
            q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
            k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
            if fb is not None:
                fb = jnp.pad(fb, ((0, 0), (0, 0), (0, pad_q), (0, pad_k)),
                             constant_values=_NEG)
            if pad_k:
                if kvb is None:
                    kvb = jnp.zeros((1, lk), jnp.float32)
                kvb = jnp.pad(kvb, ((0, 0), (0, pad_k)), constant_values=_NEG)
        has_kvb = kvb is not None
        has_fb = fb is not None
        if has_fb and fb.shape[1] > 1 and Hq != Hkv:
            # pre-fold the head axis to match the folded row layout
            g = Hq // Hkv
            fb = fb.reshape(fb.shape[0], Hkv, g * fb.shape[2], fb.shape[3])
        cfg = (bool(causal), float(sc), rate,
               has_kvb, has_kvb and kvb.shape[0] > 1,
               has_fb, has_fb and fb.shape[0] > 1,
               has_fb and fb.shape[1] > 1)
        d = _dummy()
        seed_arr = jnp.asarray(seed_val, jnp.float32).reshape(1, 1)
        out = _flash_core(cfg, q, k, v,
                          kvb if has_kvb else d, fb if has_fb else d, seed_arr)
        return out[:, :lq] if pad_q else out

    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    if isinstance(query, Tensor):
        return apply_op(run, *args)
    return run(*[a._value if isinstance(a, Tensor) else a for a in args])
