"""Ragged paged attention: ONE attention primitive for mixed
chunked-prefill + decode batches over the paged KV pool.

TPU-native port of the Ragged Paged Attention design (arxiv 2604.15464):
every batch row carries (already-cached length, new-token count) —
a decode row is new_len=1, a prefill chunk new_len=W — so a single
kernel invocation serves both kinds of row with no length-bucketed
dispatch. Query j of row i sits at absolute position ``start[i] + j``
and attends causally over the row's own pages (kpos <= qpos); rows are
fully independent, so a token's attention output does not depend on the
window width W, the batch composition, or whether it was computed as a
decode tick or inside a prefill chunk — the schedule-independence the
serving engine's byte-identical equivalence tests pin.

Shapes:
  q               : (n, W, H, D)   new-token queries (row-local window)
  k_pages/v_pages : (P, page_size, H, D)  one layer's page pool, OR an
                    int8 pool as the tuple (pages int8, scales f32
                    (P, page_size)) — per-token write-time scales (the
                    serving decoder's kv_quant="int8" layout), OR an
                    int4 pool as the tuple (nibble-packed uint8
                    (P, page_size, PB), per-GROUP scales f32
                    (P, page_size, G)) — the kv_quant="int4" layout
                    (`serving.decoder._quantize_kv_int4`). Dequant
                    happens per page next to the shared per-page
                    update, so the dequantized pool never materializes
                    in HBM
  page_table      : (n, max_pages) int32 page ids per row
  start           : (n,)           already-cached length per row

Math: an online-softmax (flash) accumulation over the row's pages, in
f32. The jnp reference (`use_kernel=False`, the CPU/production-default
path) runs EXACTLY the same per-page update as the Pallas kernel via a
`lax.scan` over pages — same operation order, same masking, same
epsilon — so interpret-mode Pallas is bit-identical to the reference
(test-pinned, the w4_matmul discipline). The kernel keeps the page pool
in HBM and streams ONE page of K/V per grid step through VMEM via the
scalar-prefetched page table (the `paged_attention` scalar-prefetch
pattern), with online-softmax state in VMEM scratch across the page
steps.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._fallback import kernel_fallback

__all__ = ["ragged_paged_attention", "ragged_paged_attention_packed"]

# softmax-denominator floor shared by reference and kernel: a row whose
# every key is masked (possible only for padded queries past true_len —
# row-local garbage by design) divides by this instead of 0
_DENOM_EPS = 1e-30
_MASK = -1e30


def _page_update(m, s, acc, logits, v, kpos, qpos, k_scale=None,
                 v_scale=None):
    """ONE page's online-softmax update — the shared math of the jnp
    reference and the Pallas kernel (they call this same function, so
    the two paths cannot drift; bit-identity rides on it).

    m/s/acc: running max [..., W, 1], denominator [..., W, 1], value
    accumulator [..., W, D]. logits [..., W, ps] this page's scores
    (q*scale @ k^T), v [..., ps, D] this page's values, kpos [ps] the
    page's absolute key positions, qpos [..., W] the queries' absolute
    positions. Causal: a query attends to kpos <= qpos only.

    k_scale/v_scale (optional): this page's per-token dequant scales,
    broadcastable to [..., ps] — the int8 KV pool's write-time scales.
    Applied HERE, so the reference and the kernel share one dequant
    exactly like they share the softmax math: logits computed from raw
    int8 keys pick up the key scale (q·(k_q·s) == (q·k_q)·s), values
    dequantize before the accumulator dot, and the dequantized pool
    never exists outside this page-sized working set."""
    if k_scale is not None:
        logits = logits * k_scale[..., None, :]
    if v_scale is not None:
        v = v * v_scale[..., :, None]
    mask = kpos[..., None, :] <= qpos[..., :, None]       # [..., W, ps]
    logits = jnp.where(mask, logits, _MASK)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    p = jnp.exp(logits - m_new)
    corr = jnp.exp(m - m_new)
    s_new = s * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jax.lax.dot_general(
        p, v, (((p.ndim - 1,), (v.ndim - 2,)),
               (tuple(range(p.ndim - 2)), tuple(range(v.ndim - 2)))),
        preferred_element_type=jnp.float32)
    return m_new, s_new, acc_new


def _dequant_page_int4(packed, gscale, heads):
    """ONE page's int4 dequant — shared by the jnp reference and the
    Pallas kernel exactly like `_page_update` (both call this same
    function immediately before it, so the two paths cannot drift and
    bit-identity extends to the nibble-packed pool).

    packed [..., ps, PB] uint8 nibble pairs (low nibble = element 2i —
    `serving.decoder._pack_int4`'s layout), gscale [..., ps, G] f32
    per-group write-time scales, heads = (H, D). Returns f32
    [..., ps, H, D].

    Unlike the int8 pool's per-TOKEN scale — a scalar that commutes out
    of the q·k contraction, so `_page_update` can apply it to the
    finished logits — an int4 group scale varies ALONG the contraction
    (groups tile the flattened H*D axis), so K must dequantize before
    the logits dot and V before the accumulator dot. Everything here is
    elementwise and exact in f32 (integer unpack, one cast, one
    multiply), so ref == kernel bit-identity needs only this function
    to be shared."""
    H, D = int(heads[0]), int(heads[1])
    PB = packed.shape[-1]
    G = gscale.shape[-1]
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    q = jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (2 * PB,)).astype(jnp.float32)
    # stored group width: 2*PB == G*group except when the pack-parity
    # nibble padded an odd G*group — only possible at G == 1, where the
    # wider pseudo-group is harmless (the pad nibble is 0 and the H*D
    # slice below drops it)
    group = (2 * PB) // G
    g = q.reshape(packed.shape[:-1] + (G, group)) * gscale[..., None]
    flat = g.reshape(packed.shape[:-1] + (G * group,))[..., :H * D]
    return flat.reshape(packed.shape[:-1] + (H, D))


# page counts up to this unroll the reference's page loop into straight
# line code (XLA fuses across pages; a lax.scan pays while-loop overhead
# per page — measurable on CPU where the decode tick is host-bound).
# Unrolled and scanned variants run the IDENTICAL op sequence, so both
# stay bit-identical to the kernel's grid walk.
_UNROLL_PAGES = 32


def _ragged_ref(q, k_pages, v_pages, page_table, start, scale,
                k_scale=None, v_scale=None, int4=False):
    """jnp reference: the kernel's page loop as an unrolled loop (small
    tables) or a lax.scan — the same per-page update in the same order
    either way (see _page_update). With an int8 pool, `k_scale`/
    `v_scale` [P, ps] carry the per-token write-time scales; the gather
    stays int8 and only one page dequantizes per step. With an int4
    pool (`int4=True`) the payload is nibble-packed [P, ps, PB] and
    `k_scale`/`v_scale` [P, ps, G] carry per-GROUP scales; each page
    dequantizes through the shared `_dequant_page_int4` before its
    update — the gather stays packed, one page unpacks per step."""
    n, W, H, D = q.shape
    ps = k_pages.shape[1]
    MP = page_table.shape[1]
    safe = jnp.maximum(page_table, 0)
    quantized = k_scale is not None and not int4
    if int4:
        # packed payload [n, MP, ps, PB] -> per-page [MP][n, ps, PB];
        # group scales [n, MP, ps, G] -> per-page [MP][n, ps, G]
        kg = jnp.moveaxis(k_pages[safe], 1, 0)
        vg = jnp.moveaxis(v_pages[safe], 1, 0)
        ksg = jnp.moveaxis(k_scale[safe], 1, 0)
        vsg = jnp.moveaxis(v_scale[safe], 1, 0)
    else:
        # [n, MP, ps, H, D] -> per-page [MP][n, H, ps, D]
        kg = jnp.moveaxis(k_pages[safe], (1, 3), (0, 2))
        vg = jnp.moveaxis(v_pages[safe], (1, 3), (0, 2))
        if quantized:
            # [n, MP, ps] -> per-page [MP][n, ps]
            ksg = jnp.moveaxis(k_scale[safe], 1, 0)
            vsg = jnp.moveaxis(v_scale[safe], 1, 0)
    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # [n,H,W,D]
    qpos = (start[:, None] + jnp.arange(W))[:, None, :]         # [n,1,W]

    def page_step(carry, inputs):
        m, s, acc = carry
        if int4:
            j, kj, vj, ksj, vsj = inputs       # [n, ps, PB], [n, ps, G]
            # barrier: the dequantized page must MATERIALIZE before the
            # dot. Without it XLA fuses the group-scale multiply into
            # the contraction and the fused gemm's rounding shifts with
            # the window shape (observed: last-ulp drift at G > 1) —
            # breaking both ref==kernel bit-identity and the
            # W-independence the schedule-equivalence tests pin. The
            # interpret-mode kernel runs op-by-op (dequant, then dot),
            # so the barrier makes the compiled ref match it exactly.
            kj = jax.lax.optimization_barrier(
                _dequant_page_int4(kj, ksj, (H, D))).transpose(0, 2, 1, 3)
            vj = jax.lax.optimization_barrier(
                _dequant_page_int4(vj, vsj, (H, D))).transpose(0, 2, 1, 3)
        elif quantized:
            j, kj, vj, ksj, vsj = inputs
        else:
            j, kj, vj = inputs                 # [n, H, ps, D]
        logits = jax.lax.dot_general(
            qf, kj.astype(jnp.float32),
            (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)          # [n, H, W, ps]
        kpos = j * ps + jnp.arange(ps)
        return _page_update(
            m, s, acc, logits, vj.astype(jnp.float32), kpos, qpos,
            # [n, ps] -> [n, 1, ps]: broadcast over the head axis
            k_scale=ksj[:, None] if quantized else None,
            v_scale=vsj[:, None] if quantized else None), None

    carry = (jnp.full((n, H, W, 1), _MASK, jnp.float32),
             jnp.zeros((n, H, W, 1), jnp.float32),
             jnp.zeros((n, H, W, D), jnp.float32))
    pages = (kg, vg) + ((ksg, vsg) if (quantized or int4) else ())
    if MP <= _UNROLL_PAGES:
        for j in range(MP):
            carry, _ = page_step(carry, (j,) + tuple(x[j] for x in pages))
    else:
        carry, _ = jax.lax.scan(page_step, carry,
                                (jnp.arange(MP),) + pages)
    m, s, acc = carry
    out = acc / jnp.maximum(s, _DENOM_EPS)               # [n, H, W, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)     # [n, W, H, D]


# the reference always executes COMPILED, even when the caller is
# eager: op-by-op dispatch rounds a hair differently from XLA's fused
# lowering, and the bit-identity contract with the interpret-mode
# kernel (which runs compiled) is pinned at the compiled semantics.
# Inside a jitted caller (the decoder's programs) this inlines away.
_ragged_ref_jit = jax.jit(_ragged_ref, static_argnames=("scale", "int4"))


def _ragged_kernel(pt_ref, start_ref, q_ref, k_ref, v_ref, *rest,
                   scale, page_size, max_pages, quant, heads=None):
    """Grid (n, H, max_pages): one page of K/V in VMEM per step, online
    softmax in scratch — the scalar-prefetched page_table drives the
    K/V BlockSpec index maps, so the pool never leaves HBM whole.
    `quant` is the pool's mode (None | "int8" | "int4"). int8: two more
    page-indexed refs carry the [ps] per-token scales; dequant runs
    inside `_page_update`, on the one VMEM-resident page — the f32
    pool never exists. int4: the page block is the WHOLE packed page
    (nibbles mix heads — the [ps, PB] payload plus [ps, G] group-scale
    refs stream via their own page-indexed BlockSpecs), the nibble
    unpack + group dequant run in VMEM through the shared
    `_dequant_page_int4`, and the body slices its own head (grid axis
    1; `heads` = (H, D) — every grid step along H re-reads the same
    packed page, an interpret-mode correctness cost a production TPU
    kernel would fold into a head-blocked grid)."""
    from jax.experimental import pallas as pl

    if quant:
        ks_ref, vs_ref, o_ref, m_scr, s_scr, acc_scr = rest
    else:
        o_ref, m_scr, s_scr, acc_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASK)
        s_scr[...] = jnp.zeros_like(s_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale        # [W, D]
    if quant == "int4":
        hi = pl.program_id(1)
        kd = _dequant_page_int4(k_ref[0], ks_ref[0], heads)  # [ps, H, D]
        vd = _dequant_page_int4(v_ref[0], vs_ref[0], heads)
        k = jax.lax.dynamic_index_in_dim(kd, hi, 1, keepdims=False)
        v = jax.lax.dynamic_index_in_dim(vd, hi, 1, keepdims=False)
    else:
        k = k_ref[0, :, 0, :].astype(jnp.float32)            # [ps, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
    logits = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    kpos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)[0]                     # [ps]
    W = q.shape[0]
    qpos = start_ref[b] + jax.lax.broadcasted_iota(
        jnp.int32, (W, 1), 0)[:, 0]                          # [W]
    m_new, s_new, acc_new = _page_update(
        m_scr[...], s_scr[...], acc_scr[...], logits, v, kpos, qpos,
        k_scale=ks_ref[0, :] if quant == "int8" else None,
        v_scale=vs_ref[0, :] if quant == "int8" else None)
    m_scr[...] = m_new
    s_scr[...] = s_new
    acc_scr[...] = acc_new

    @pl.when(j == max_pages - 1)
    def _emit():
        out = acc_scr[...] / jnp.maximum(s_scr[...], _DENOM_EPS)
        o_ref[0, :, 0, :] = out.astype(o_ref.dtype)


def _ragged_kernel_call(q, k_pages, v_pages, page_table, start, scale,
                        interpret, k_scale=None, v_scale=None,
                        int4=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, W, H, D = q.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    quant = "int4" if int4 else ("int8" if k_scale is not None else None)

    def page_map(bi, hi, j, pt, st):
        return (jnp.maximum(pt[bi, j], 0), 0, hi, 0)

    def scale_map(bi, hi, j, pt, st):
        return (jnp.maximum(pt[bi, j], 0), 0)

    def packed_map(bi, hi, j, pt, st):
        # int4 blocks carry the whole page (nibble groups mix heads):
        # page-indexed on axis 0, full ps x PB/G extent
        return (jnp.maximum(pt[bi, j], 0), 0, 0)

    in_specs = [
        pl.BlockSpec((1, W, 1, D),
                     lambda bi, hi, j, pt, st: (bi, 0, hi, 0)),
    ]
    if int4:
        PB = k_pages.shape[-1]
        G = k_scale.shape[-1]
        in_specs += [pl.BlockSpec((1, page_size, PB), packed_map),
                     pl.BlockSpec((1, page_size, PB), packed_map),
                     pl.BlockSpec((1, page_size, G), packed_map),
                     pl.BlockSpec((1, page_size, G), packed_map)]
        operands = (q, k_pages, v_pages, k_scale, v_scale)
    else:
        in_specs += [pl.BlockSpec((1, page_size, 1, D), page_map),
                     pl.BlockSpec((1, page_size, 1, D), page_map)]
        operands = (q, k_pages, v_pages)
        if quant:
            in_specs += [pl.BlockSpec((1, page_size), scale_map),
                         pl.BlockSpec((1, page_size), scale_map)]
            operands += (k_scale, v_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # page_table, start
        grid=(n, H, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, W, 1, D), lambda bi, hi, j, pt, st: (bi, 0, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((W, 1), jnp.float32),
            pltpu.VMEM((W, 1), jnp.float32),
            pltpu.VMEM((W, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_ragged_kernel, scale=scale,
                          page_size=page_size, max_pages=max_pages,
                          quant=quant, heads=(H, D)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, W, H, D), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), start.astype(jnp.int32),
      *operands)


def _packed_kernel_call(q2, k_pages, v_pages, page_table, row_ids, pos,
                        scale, interpret, k_scale=None, v_scale=None,
                        int4=False):
    """Pallas call for the PACKED layout: grid (T, H, max_pages) — one
    token's one page per step. The page/scale BlockSpec index maps
    indirect through TWO scalar-prefetched vectors: `row_ids[t]` picks
    the token's page-table ROW, `page_table[row, j]` the page — so the
    [n, max_pages] table never gets gathered to a [T, max_pages] copy
    in HBM; the indirection lives entirely in the prefetched scalars.
    The kernel BODY is `_ragged_kernel` itself (pos plays the dense
    path's start role; the rid prefetch is consumed only by the index
    maps), so the per-page math cannot drift from the dense kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, W, H, D = q2.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    quant = "int4" if int4 else ("int8" if k_scale is not None else None)

    def page_map(ti, hi, j, pt, rid, ps_):
        return (jnp.maximum(pt[rid[ti], j], 0), 0, hi, 0)

    def scale_map(ti, hi, j, pt, rid, ps_):
        return (jnp.maximum(pt[rid[ti], j], 0), 0)

    def packed_map(ti, hi, j, pt, rid, ps_):
        return (jnp.maximum(pt[rid[ti], j], 0), 0, 0)

    in_specs = [
        pl.BlockSpec((1, W, 1, D),
                     lambda ti, hi, j, pt, rid, ps_: (ti, 0, hi, 0)),
    ]
    if int4:
        PB = k_pages.shape[-1]
        G = k_scale.shape[-1]
        in_specs += [pl.BlockSpec((1, page_size, PB), packed_map),
                     pl.BlockSpec((1, page_size, PB), packed_map),
                     pl.BlockSpec((1, page_size, G), packed_map),
                     pl.BlockSpec((1, page_size, G), packed_map)]
        operands = (q2, k_pages, v_pages, k_scale, v_scale)
    else:
        in_specs += [pl.BlockSpec((1, page_size, 1, D), page_map),
                     pl.BlockSpec((1, page_size, 1, D), page_map)]
        operands = (q2, k_pages, v_pages)
        if quant:
            in_specs += [pl.BlockSpec((1, page_size), scale_map),
                         pl.BlockSpec((1, page_size), scale_map)]
            operands += (k_scale, v_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,   # page_table, row_ids, pos
        grid=(T, H, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, W, 1, D), lambda ti, hi, j, pt, rid, ps_: (ti, 0, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((W, 1), jnp.float32),
            pltpu.VMEM((W, 1), jnp.float32),
            pltpu.VMEM((W, D), jnp.float32),
        ],
    )

    def body(pt_ref, rid_ref, pos_ref, *args):
        # rid_ref is consumed by the index maps only; the body is the
        # dense kernel with `pos` in the start slot
        return _ragged_kernel(pt_ref, pos_ref, *args, scale=scale,
                              page_size=page_size, max_pages=max_pages,
                              quant=quant, heads=(H, D))

    return pl.pallas_call(
        body, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, W, H, D), q2.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), row_ids.astype(jnp.int32),
      pos.astype(jnp.int32), *operands)


def ragged_paged_attention_packed(q, k_pages, v_pages, page_table,
                                  row_ids, pos, scale=None,
                                  use_kernel=False, interpret=None):
    """PACKED-layout causal attention over paged KV: q [T, H, D] is a
    flat stream of new tokens — token t belongs to batch row
    `row_ids[t]` (its row in `page_table` [n, max_pages]) and sits at
    absolute position `pos[t]`. No [n, W] window padding exists in the
    layout at all: a pure-decode batch pays exactly n tokens, a mixed
    batch pays exactly its token total (the Ragged Paged Attention
    layout, arxiv 2604.15464 — pay for tokens, not windows).

    Per-token math is EXACTLY the dense path's: each token runs the
    same per-page `_page_update` walk over its row's pages that a W=1
    window would (padded internally to the same 2-wide window the
    dense W=1 path uses), so a token's output is bit-identical to the
    dense `ragged_paged_attention` computing the same position inside
    any window width — the packed/dense byte-identity the serving
    engine's A/B twin pins. The Pallas kernel scalar-prefetches
    `row_ids` and `pos` next to the page table and resolves
    `page_table[row_ids[t], j]` inside the BlockSpec index maps (see
    `_packed_kernel_call`). int8/int4 pools pass as (pages, scales)
    tuples exactly like the dense entry point. Returns [T, H, D]."""
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    row_ids = jnp.asarray(row_ids, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    ks = vs = None
    int4 = False
    if isinstance(k_pages, tuple):
        k_pages, ks = k_pages
        v_pages, vs = v_pages
        int4 = k_pages.dtype == jnp.uint8    # nibble-packed payload
    # the same 2-wide padding the dense W=1 path uses (degenerate
    # matvec lowering drifts a ulp at W=1): one zero query per token,
    # discarded — bit-identity with the dense path rides on both
    # layouts running the identical W=2 program shape per position
    q2 = jnp.stack([q, jnp.zeros_like(q)], axis=1)      # [T, 2, H, D]
    if not use_kernel:
        # reference: per-token table rows via one gather; the page walk
        # is the dense reference's (`_page_update` via _ragged_ref)
        table_tok = page_table[row_ids]                 # [T, max_pages]
        return _ragged_ref_jit(q2, k_pages, v_pages, table_tok, pos,
                               scale=float(scale), k_scale=ks,
                               v_scale=vs, int4=int4)[:, 0]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    try:
        return _packed_kernel_call(q2, k_pages, v_pages, page_table,
                                   row_ids, pos, scale, interpret,
                                   k_scale=ks, v_scale=vs,
                                   int4=int4)[:, 0]
    except Exception as e:
        kernel_fallback("ragged_paged_attention_packed", e)
        table_tok = page_table[row_ids]
        return _ragged_ref_jit(q2, k_pages, v_pages, table_tok, pos,
                               scale=float(scale), k_scale=ks,
                               v_scale=vs, int4=int4)[:, 0]


def ragged_paged_attention(q, k_pages, v_pages, page_table, start,
                           scale=None, use_kernel=False, interpret=None):
    """Causal attention of ragged new-token windows over paged KV.

    q (n, W, H, D): row i's new tokens at positions start[i]..start[i]+
    W-1 (pad the window past the row's true new_len — padded queries
    produce row-local garbage the caller discards, exactly like padded
    positions in the chunked prefill). Decode rows are simply W=1 (or a
    width-W window with one real query). Returns (n, W, H, D).

    `k_pages`/`v_pages` may each be a quantized pool tuple: int8 as
    (pages int8, scales f32 [P, ps]) — the serving decoder's
    kv_quant="int8" layout — or int4 as (nibble-packed uint8
    [P, ps, PB], per-group scales f32 [P, ps, G]) — kv_quant="int4".
    Both paths dequantize per page next to the shared `_page_update`
    (int8 inside it, int4 through `_dequant_page_int4` right before
    it — group scales cannot be folded post-dot)."""
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    start = jnp.asarray(start, jnp.int32)
    if q.shape[1] == 1:
        # degenerate single-query windows (the all-decode batch) tickle
        # a different XLA CPU matvec lowering in the reference program
        # than in the interpret-mode kernel (observed: last-ulp drift at
        # W=1, bit-identical at W>=2). Pad with one discarded zero query
        # on BOTH paths — queries are row-local, so row 0's math is
        # unchanged and the two paths stay bit-identical everywhere.
        q2 = jnp.concatenate([q, jnp.zeros_like(q)], axis=1)
        return ragged_paged_attention(q2, k_pages, v_pages, page_table,
                                      start, scale=scale,
                                      use_kernel=use_kernel,
                                      interpret=interpret)[:, :1]
    ks = vs = None
    int4 = False
    if isinstance(k_pages, tuple):
        k_pages, ks = k_pages
        v_pages, vs = v_pages
        int4 = k_pages.dtype == jnp.uint8    # nibble-packed payload
    if not use_kernel:
        return _ragged_ref_jit(q, k_pages, v_pages, page_table, start,
                               scale=float(scale), k_scale=ks,
                               v_scale=vs, int4=int4)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    try:
        return _ragged_kernel_call(q, k_pages, v_pages, page_table,
                                   start, scale, interpret,
                                   k_scale=ks, v_scale=vs, int4=int4)
    except Exception as e:
        kernel_fallback("ragged_paged_attention", e)
        return _ragged_ref_jit(q, k_pages, v_pages, page_table, start,
                               scale=float(scale), k_scale=ks,
                               v_scale=vs, int4=int4)
