"""Block-sparse (blocked-CSR) attention Pallas kernel.

Reference: python/paddle/nn/functional/sparse_attention.py backed by
paddle/fluid/operators/sparse_attention_op.cu (per-row CSR softmax(QK^T)V
on CUDA).  TPU-native design: sparsity at MXU-tile granularity — each
q-block row carries a padded list of nonzero kv-block indices, and the
flash-style online-softmax inner loop visits ONLY those blocks via
dynamic VMEM slices, so compute and VMEM traffic scale with nnz blocks
instead of L^2.  The blocked-CSR indices ride in as scalar-prefetch
operands (same pattern as ops/paged_attention.py).

Layout matches the reference op: q/k/v are [B, H, L, D].

  block_cols   : [G, nq, max_nnz] int32, kv-block ids per q-block row
                 (right-padded; pad value arbitrary in [0, nk))
  block_counts : [G, nq]          int32, valid entries per row
  G = B*H for per-(batch,head) patterns, or 1 for a shared pattern.

Backward runs a dense-masked recompute in jnp (the sparsity mask is
rebuilt from the same blocked CSR), so training through the kernel is
exact; a block-sparse backward kernel can replace it without API change.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["block_sparse_attention", "block_mask_from_csr",
           "csr_to_block_layout", "dense_mask_sparse_attention"]

_NEG = -1e30


def _bs_fwd_kernel(cols_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, *,
                   block_size, max_nnz, scale, gs_b, gs_h):
    from jax.experimental import pallas as pl

    b, h, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    g = b * gs_b + h * gs_h
    bs = block_size
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
    bq, D = q.shape
    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, D), jnp.float32)
    n_valid = cnt_ref[g, i]

    def body(j, carry):
        m, l, acc = carry
        c = cols_ref[g, i, j]
        kb = k_ref[0, 0, pl.ds(c * bs, bs), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(c * bs, bs), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        valid = j < n_valid
        s = jnp.where(valid, s, _NEG)                    # padded slot
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        # explicit zero for padded slots: when no valid block has been
        # seen yet, s == m_new == _NEG and exp(s - m_new) would be 1
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, max_nnz, body, (m0, l0, a0))
    # fully-masked row (count 0): emit zeros rather than NaN
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _bs_fwd(q, k, v, block_cols, block_counts, block_size, scale,
            interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, H, L, D = q.shape
    bs = block_size
    G, nq, max_nnz = block_cols.shape
    assert L % bs == 0 and nq == L // bs, (L, bs, nq)
    gs_b = H if G == B * H else 0
    gs_h = 1 if G == B * H else 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_cols, block_counts
        grid=(B, H, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bs, D), lambda b, h, i, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, L, D), lambda b, h, i, *_: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, L, D), lambda b, h, i, *_: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bs, D),
                               lambda b, h, i, *_: (b, h, i, 0)),
    )
    return pl.pallas_call(
        functools.partial(_bs_fwd_kernel, block_size=bs, max_nnz=max_nnz,
                          scale=scale, gs_b=gs_b, gs_h=gs_h),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, L, D), q.dtype),
        interpret=interpret,
    )(block_cols.astype(jnp.int32), block_counts.astype(jnp.int32),
      q, k, v)


def block_mask_from_csr(block_cols, block_counts, nk):
    """[G, nq, nk] bool block mask from the padded blocked-CSR arrays."""
    G, nq, max_nnz = block_cols.shape
    valid = (jnp.arange(max_nnz)[None, None, :]
             < block_counts[:, :, None])                      # [G,nq,nnz]
    onehot = jax.nn.one_hot(block_cols, nk, dtype=jnp.bool_)  # [G,nq,nnz,nk]
    return jnp.any(onehot & valid[..., None], axis=2)


def _dense_recompute(q, k, v, block_cols, block_counts, block_size, scale):
    """Dense-masked attention with the SAME sparsity (golden path + the
    backward rule's recompute)."""
    B, H, L, D = q.shape
    nk = L // block_size
    bm = block_mask_from_csr(block_cols, block_counts, nk)    # [G,nq,nk]
    em = jnp.repeat(jnp.repeat(bm, block_size, axis=1),
                    block_size, axis=2)                       # [G, L, L]
    em = em.reshape((B, H, L, L)) if bm.shape[0] == B * H \
        else em[:, None, :, :]                                # broadcast H
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(em, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.maximum(l, 1e-30)
    # fully-masked rows: all-equal logits would give uniform weights
    p = jnp.where(em, p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _bs_attention(q, k, v, block_cols, block_counts, block_size, scale,
                  interpret):
    return _bs_fwd(q, k, v, block_cols, block_counts, block_size, scale,
                   interpret)


def _bs_attention_fwd(q, k, v, block_cols, block_counts, block_size, scale,
                      interpret):
    out = _bs_fwd(q, k, v, block_cols, block_counts, block_size, scale,
                  interpret)
    return out, (q, k, v, block_cols, block_counts)


def _bs_attention_bwd(block_size, scale, interpret, res, g):
    q, k, v, block_cols, block_counts = res
    grads = jax.vjp(
        lambda qq, kk, vv: _dense_recompute(qq, kk, vv, block_cols,
                                            block_counts, block_size,
                                            scale),
        q, k, v)[1](g)
    return grads + (None, None)


_bs_attention.defvjp(_bs_attention_fwd, _bs_attention_bwd)


def block_sparse_attention(q, k, v, block_cols, block_counts, block_size,
                           scale=None, interpret=None):
    """softmax(QK^T / sqrt(d)) V restricted to the given kv blocks per
    q-block row.  q/k/v: [B, H, L, D]; see module docstring for the
    blocked-CSR layout.  Differentiable (dense-masked recompute bwd)."""
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    return _bs_attention(q, k, v, jnp.asarray(block_cols, jnp.int32),
                         jnp.asarray(block_counts, jnp.int32),
                         int(block_size), float(scale), interpret)


def dense_mask_sparse_attention(q, k, v, mask, key_padding_mask=None,
                                attn_mask=None, scale=None):
    """Reference-semantics fallback: element-level mask [B, H, L, L]
    (True = attend), optional key_padding_mask [B, L] and attn_mask
    [L, L] with 0 = masked (reference sparse_attention args)."""
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if key_padding_mask is not None:
        mask = mask & (key_padding_mask[:, None, None, :] != 0)
    if attn_mask is not None:
        mask = mask & (attn_mask[None, None, :, :] != 0)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = jnp.where(mask, p / jnp.maximum(l, 1e-30), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def csr_element_mask(offset, columns, seq_len):
    """[B, H, L, L] bool mask from an element-level CSR pattern
    (traceable — used by the dense fallback when the CSR arrays are
    traced or not block-aligned)."""
    offset = jnp.asarray(offset)
    columns = jnp.asarray(columns)
    B, H, _ = offset.shape
    nnz = columns.shape[-1]
    idx = jnp.arange(nnz)

    def rows_of(off):
        return jnp.searchsorted(off, idx, side="right") - 1

    rows = jax.vmap(jax.vmap(rows_of))(offset)            # [B, H, nnz]
    bi = jnp.arange(B)[:, None, None]
    hi = jnp.arange(H)[None, :, None]
    mask = jnp.zeros((B, H, seq_len, seq_len), bool)
    # entries past offset[-1] resolve to row==L and are dropped
    return mask.at[bi, hi, rows, columns].set(True, mode="drop")


def csr_to_block_layout(offset, columns, seq_len, block_sizes=(128, 64, 32, 16, 8)):
    """Detect whether a CONCRETE element-level CSR pattern (reference
    sparse_attention layout: offset [B,H,L+1], columns [B,H,nnz]) is
    exactly block-aligned for some block size; if so return
    (block_size, block_cols [B*H,nq,max_nnz], block_counts [B*H,nq]),
    else None.  numpy-only — call outside jit."""
    offset = np.asarray(offset)
    columns = np.asarray(columns)
    B, H, Lp1 = offset.shape
    L = seq_len
    dense = np.zeros((B * H, L, L), bool)
    off = offset.reshape(B * H, Lp1)
    cols = columns.reshape(B * H, -1)
    for g in range(B * H):
        for r in range(L):
            dense[g, r, cols[g, off[g, r]:off[g, r + 1]]] = True
    for bs in block_sizes:
        if L % bs:
            continue
        nb = L // bs
        blocks = dense.reshape(B * H, nb, bs, nb, bs)
        anyb = blocks.any(axis=(2, 4))
        allb = blocks.all(axis=(2, 4))
        if not (anyb == allb).all():
            continue   # partially-filled block: not aligned at this size
        counts = anyb.sum(axis=-1).astype(np.int32)          # [G, nb]
        max_nnz = max(1, int(counts.max()))
        colsb = np.zeros((B * H, nb, max_nnz), np.int32)
        for g in range(B * H):
            for r in range(nb):
                idx = np.nonzero(anyb[g, r])[0]
                colsb[g, r, :len(idx)] = idx
        return bs, colsb, counts
    return None
