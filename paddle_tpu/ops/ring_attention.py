"""Ring attention: exact flash attention over a sequence-sharded 'sp' axis.

Replaces the reference's sequence-parallel attention (fleet sep/
sparse_attention CUDA paths) with the TPU-native ring algorithm: K/V shards
rotate around the ICI ring via ppermute while each device accumulates its
queries' online-softmax partials — memory O(L/sp), comms overlap with compute.

Two causal work layouts:

- contiguous (ring_attention_local): shard d holds tokens
  [d·L/S, (d+1)·L/S). Every ring step computes the full Lq×Lk block and
  masks — correct and simple, but ~half the computed blocks are fully
  masked.
- zigzag (zigzag_ring_attention_local): the sequence is split into 2S
  half-chunks and shard d holds chunks (d, 2S-1-d). Step 0 is plain local
  causal attention; every later step needs exactly TWO unmasked
  half-blocks per device (one always qc1×kc0; the other qc0×kc0 when the
  visiting shard is earlier, qc1×kc1 when later) — uniform load, no
  fully-masked matmuls, ~2× less attention compute at large sp. Same
  exact online-softmax math, so results match contiguous bit-for-bit up
  to float reassociation.

Used inside shard_map with q/k/v sharded on the sequence dim:
    out = shard_map(partial(ring_attention_local, axis_name="sp", causal=True),
                    mesh, in_specs=P(dp, "sp", None, None), ...)(q, k, v)
Layout: [batch, seq_local, heads, head_dim].
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

def _axis_size(axis_name):
    """Static size of a shard_map axis: `jax.lax.axis_size` on jax >= 0.6;
    on 0.4.x, psum of a literal 1 (constant-folded to the static size)."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


__all__ = ["ring_attention_local", "ring_attention",
           "ring_flash_attention_local", "zigzag_ring_attention_local",
           "zigzag_ring_flash_attention_local"]


def ring_flash_attention_local(q, k, v, axis_name="sp", causal=True,
                               scale=None):
    """Flash-kernel ring attention INSIDE shard_map (the long-context
    path): each ring step runs the Pallas flash kernel on the resident
    K/V shard and merges (out, lse) partials by log-sum-exp, so nothing
    of size Lq×Lk is ever materialized — per-device memory stays
    O(L/sp · D). Fully-masked causal steps are skipped via lax.cond.

    The custom VJP is the ring form of the flash backward: gradients for
    a (q-shard, kv-shard) block pair computed with the GLOBAL lse are
    exact partials of the global softmax, so dk/dv accumulators simply
    rotate with their K/V shards and arrive home after the full cycle.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if q.shape[2] != k.shape[2]:
        # GQA head-folding inside the per-step impls would break the lse
        # merge bookkeeping; the dense path handles it
        return ring_attention_local(q, k, v, axis_name, causal, scale,
                                    use_flash=False)
    out, _ = _ring_flash(q, k, v, axis_name, causal, float(scale))
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, scale):
    return _ring_flash_fwd_compute(q, k, v, axis_name, causal, scale)


def _ring_flash_fwd_compute(q, k, v, axis_name, causal, scale):
    from .attention import _flash_fwd_lse_impl

    sp = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # step 0: this shard's own block — causal square
    out0, lse0 = _flash_fwd_lse_impl(q, k, v, causal, scale)
    acc0 = jnp.swapaxes(out0, 1, 2).astype(jnp.float32)   # [B,H,Lq,D]
    L0 = lse0                                             # [B,H,Lq,1] f32

    def body(step, carry):
        k_cur, v_cur, acc, L_run = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my_idx - step) % sp

        def merge(args):
            acc, L_run = args
            out_i, lse_i = _flash_fwd_lse_impl(q, k_cur, v_cur, False, scale)
            return _lse_merge(acc, L_run, out_i, lse_i)

        if causal:
            # skip blocks where every kv position is in the future
            acc, L_run = jax.lax.cond(src < my_idx, merge, lambda a: a,
                                      (acc, L_run))
        else:
            acc, L_run = merge((acc, L_run))
        return k_cur, v_cur, acc, L_run

    _, _, acc, L_tot = jax.lax.fori_loop(1, sp, body, (k, v, acc0, L0))
    out = jnp.swapaxes(acc, 1, 2).astype(q.dtype)         # [B,Lq,H,D]
    return out, L_tot


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_flash_fwd_compute(q, k, v, axis_name, causal, scale)
    return (out, lse), (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, cts):
    from .attention import _flash_bwd_impl

    q, k, v, out, lse = res
    g = cts[0].astype(q.dtype)   # lse cotangent is zero in ring use
    sp = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # step 0: own causal block
    dq0, dk0, dv0 = _flash_bwd_impl(q, k, v, out, lse, g, causal, scale)
    dq0 = dq0.astype(jnp.float32)

    def body(step, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        src = (my_idx - step) % sp

        def compute(args):
            dk_cur, dv_cur, dq = args
            # global lse makes this block's grads exact global partials
            dq_i, dk_i, dv_i = _flash_bwd_impl(q, k_cur, v_cur, out, lse,
                                               g, False, scale)
            return (dk_cur + dk_i.astype(dk_cur.dtype),
                    dv_cur + dv_i.astype(dv_cur.dtype),
                    dq + dq_i.astype(jnp.float32))

        if causal:
            dk_cur, dv_cur, dq = jax.lax.cond(src < my_idx, compute,
                                              lambda a: a,
                                              (dk_cur, dv_cur, dq))
        else:
            dk_cur, dv_cur, dq = compute((dk_cur, dv_cur, dq))
        return k_cur, v_cur, dk_cur, dv_cur, dq

    dk0 = dk0.astype(jnp.float32)
    dv0 = dv0.astype(jnp.float32)
    # after the remaining sp-1 rotations everything is one hop short of
    # home; one final ppermute completes the cycle
    k_f, v_f, dk, dv, dq = jax.lax.fori_loop(
        1, sp, body, (k, v, dk0, dv0, dq0))
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _flash_ring_ok(q_shape, kv_heads, block_len):
    """Default-on gate for the flash ring paths: the kernel's head-dim
    tiling (ops/attention.py flash_attention_available), no GQA fold, and
    128-aligned per-step block length (the non-public impls don't pad).
    q_shape: [..., H, D] of the LOCAL q; block_len: rows per flash call
    (L_local for contiguous, L_local/2 for zigzag)."""
    H, D = q_shape[-2], q_shape[-1]
    return (D in (64, 128, 256) and H == kv_heads
            and block_len > 0 and block_len % 128 == 0)


def _lse_merge(acc, L_run, out_i, lse_i):
    """Merge a flash partial (normalized out_i [B,L,H,D], lse_i
    [B,H,L,1]) into the running (acc [B,H,L,D] f32, L_run) pair."""
    oh = jnp.swapaxes(out_i, 1, 2).astype(jnp.float32)
    L_new = jnp.logaddexp(L_run, lse_i)
    acc = acc * jnp.exp(L_run - L_new) + oh * jnp.exp(lse_i - L_new)
    return acc, L_new


def zigzag_ring_flash_attention_local(q, k, v, axis_name="sp", scale=None):
    """Flash-kernel zigzag ring (causal): the load-balanced layout AND
    O(L/sp) attention memory.  Every ring step runs exactly two Lh x Lh
    flash blocks per device (block X: q-half-1 x visiting chunk-0, always
    unmasked; block Y: the early/late where-selected half pair), partials
    merged by lse per query half.  Same custom-VJP scheme as the
    contiguous flash ring: block grads against the global per-half lse
    are exact partials, dk/dv rotate home with their shards."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if q.shape[2] != k.shape[2]:
        # GQA head-folding breaks the per-half lse bookkeeping; dense path
        return _zigzag_dense_local(q, k, v, axis_name, scale)
    out, _ = _zz_ring_flash(q, k, v, axis_name, float(scale))
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _zz_ring_flash(q, k, v, axis_name, scale):
    return _zz_ring_flash_fwd_compute(q, k, v, axis_name, scale)


def _zz_ring_flash_fwd_compute(q, k, v, axis_name, scale):
    from .attention import _flash_fwd_lse_impl

    sp = _axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    Lh = q.shape[1] // 2
    q0, q1 = q[:, :Lh], q[:, Lh:]

    def halves(t):
        return t[:, :Lh], t[:, Lh:]

    k0, k1 = halves(k)
    v0, v1 = halves(v)

    # step 0: local causal in zigzag order = three flash blocks
    o, lse = _flash_fwd_lse_impl(q0, k0, v0, True, scale)     # q0 x c_d
    acc0 = jnp.swapaxes(o, 1, 2).astype(jnp.float32)
    L0 = lse
    o, lse = _flash_fwd_lse_impl(q1, k0, v0, False, scale)    # q1 x c_d
    acc1 = jnp.swapaxes(o, 1, 2).astype(jnp.float32)
    L1 = lse
    o, lse = _flash_fwd_lse_impl(q1, k1, v1, True, scale)     # q1 x c_{2S-1-d}
    acc1, L1 = _lse_merge(acc1, L1, o, lse)

    def body(t, carry):
        k_cur, v_cur, acc0, L0, acc1, L1 = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (d - t) % sp
        kc0, kc1 = halves(k_cur)
        vc0, vc1 = halves(v_cur)
        # block X: q1 x visiting chunk src — always fully unmasked
        o, lse = _flash_fwd_lse_impl(q1, kc0, vc0, False, scale)
        acc1, L1 = _lse_merge(acc1, L1, o, lse)
        # block Y: early shard -> q0 x kc0, later -> q1 x kc1; select so
        # the flash kernel runs once
        early = src < d
        q_sel = jnp.where(early, q0, q1)
        k_sel = jnp.where(early, kc0, kc1)
        v_sel = jnp.where(early, vc0, vc1)
        a_sel = jnp.where(early, acc0, acc1)
        L_sel = jnp.where(early, L0, L1)
        o, lse = _flash_fwd_lse_impl(q_sel, k_sel, v_sel, False, scale)
        a_new, L_new = _lse_merge(a_sel, L_sel, o, lse)
        acc0 = jnp.where(early, a_new, acc0)
        L0 = jnp.where(early, L_new, L0)
        acc1 = jnp.where(early, acc1, a_new)
        L1 = jnp.where(early, L1, L_new)
        return k_cur, v_cur, acc0, L0, acc1, L1

    _, _, acc0, L0, acc1, L1 = jax.lax.fori_loop(
        1, sp, body, (k, v, acc0, L0, acc1, L1))
    out = jnp.concatenate([jnp.swapaxes(acc0, 1, 2),
                           jnp.swapaxes(acc1, 1, 2)], axis=1).astype(q.dtype)
    lse = jnp.concatenate([L0, L1], axis=2)                   # [B,H,2Lh,1]
    return out, lse


def _zz_ring_flash_fwd(q, k, v, axis_name, scale):
    out, lse = _zz_ring_flash_fwd_compute(q, k, v, axis_name, scale)
    return (out, lse), (q, k, v, out, lse)


def _zz_ring_flash_bwd(axis_name, scale, res, cts):
    from .attention import _flash_bwd_impl

    q, k, v, out, lse = res
    g = cts[0].astype(q.dtype)
    sp = _axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    Lh = q.shape[1] // 2

    def halves(t, axis=1):
        if axis == 1:
            return t[:, :Lh], t[:, Lh:]
        return t[:, :, :Lh], t[:, :, Lh:]

    q0, q1 = halves(q)
    k0, k1 = halves(k)
    v0, v1 = halves(v)
    out0, out1 = halves(out)
    g0, g1 = halves(g)
    lse0, lse1 = halves(lse, axis=2)

    # step 0: the three local blocks
    dq_a, dk_a, dv_a = _flash_bwd_impl(q0, k0, v0, out0, lse0, g0, True,
                                       scale)
    dq_b, dk_b, dv_b = _flash_bwd_impl(q1, k0, v0, out1, lse1, g1, False,
                                       scale)
    dq_c, dk_c, dv_c = _flash_bwd_impl(q1, k1, v1, out1, lse1, g1, True,
                                       scale)
    dq0 = dq_a.astype(jnp.float32)
    dq1 = (dq_b + dq_c).astype(jnp.float32)
    dk_own = jnp.concatenate([(dk_a + dk_b), dk_c], axis=1) \
        .astype(jnp.float32)
    dv_own = jnp.concatenate([(dv_a + dv_b), dv_c], axis=1) \
        .astype(jnp.float32)

    def body(t, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq0, dq1 = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        src = (d - t) % sp
        kc0, kc1 = halves(k_cur)
        vc0, vc1 = halves(v_cur)
        # block X: q1 x chunk src (full)
        dq_i, dk_i, dv_i = _flash_bwd_impl(q1, kc0, vc0, out1, lse1, g1,
                                           False, scale)
        dq1 = dq1 + dq_i.astype(jnp.float32)
        dk_cur = dk_cur.at[:, :Lh].add(dk_i.astype(jnp.float32))
        dv_cur = dv_cur.at[:, :Lh].add(dv_i.astype(jnp.float32))
        # block Y (selected half pair)
        early = src < d
        q_sel = jnp.where(early, q0, q1)
        k_sel = jnp.where(early, kc0, kc1)
        v_sel = jnp.where(early, vc0, vc1)
        o_sel = jnp.where(early, out0, out1)
        l_sel = jnp.where(early, lse0, lse1)
        g_sel = jnp.where(early, g0, g1)
        dq_i, dk_i, dv_i = _flash_bwd_impl(q_sel, k_sel, v_sel, o_sel,
                                           l_sel, g_sel, False, scale)
        dq_i = dq_i.astype(jnp.float32)
        dk_i = dk_i.astype(jnp.float32)
        dv_i = dv_i.astype(jnp.float32)
        zero = jnp.zeros_like(dk_i)
        dq0 = dq0 + jnp.where(early, dq_i, 0.0)
        dq1 = dq1 + jnp.where(early, 0.0, dq_i)
        dk_cur = dk_cur + jnp.concatenate(
            [jnp.where(early, dk_i, zero), jnp.where(early, zero, dk_i)],
            axis=1)
        dv_cur = dv_cur + jnp.concatenate(
            [jnp.where(early, dv_i, zero), jnp.where(early, zero, dv_i)],
            axis=1)
        return k_cur, v_cur, dk_cur, dv_cur, dq0, dq1

    _, _, dk, dv, dq0, dq1 = jax.lax.fori_loop(
        1, sp, body, (k, v, dk_own, dv_own, dq0, dq1))
    # complete the rotation cycle so accumulators land on their owners
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    dq = jnp.concatenate([dq0, dq1], axis=1)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_zz_ring_flash.defvjp(_zz_ring_flash_fwd, _zz_ring_flash_bwd)


def ring_attention_local(q, k, v, axis_name="sp", causal=True, scale=None,
                         use_flash=None):
    """Runs INSIDE shard_map. q,k,v: [B, L_local, H, D] (this shard).

    use_flash: route each ring step through the Pallas flash kernel with
    lse-merged partials (O(L/sp) memory — the long-context path). Default:
    on for the TPU backend when the kernel supports the shape
    (_flash_ring_ok); the dense jnp path remains for CPU tests, GQA, and
    unaligned shapes."""
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu"
                     and _flash_ring_ok(q.shape, k.shape[2], q.shape[1]))
    if use_flash:
        return ring_flash_attention_local(q, k, v, axis_name, causal, scale)
    return _ring_dense_local(q, k, v, axis_name, causal, scale)


def _ring_dense_local(q, k, v, axis_name="sp", causal=True, scale=None):
    """Dense per-step scores (materializes Lq x Lk per ring step)."""
    sp = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B,H,Lq,D]
    B, H, Lq, D = qh.shape
    Lk = k.shape[1]

    q_pos = my_idx * Lq + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)

    # derive from qh so the carry inits inherit its varying-axes type
    m0 = jnp.full_like(qh[..., :1], -1e30)
    l0 = jnp.zeros_like(qh[..., :1])
    acc0 = jnp.zeros_like(qh)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(step, carry):
        k_cur, v_cur, m, l, acc = carry
        src = (my_idx - step) % sp  # which shard's k/v we hold this step
        kh = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        s = qh @ jnp.swapaxes(kh, -1, -2)  # [B,H,Lq,Lk]
        if causal:
            k_pos = src * Lk + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + p @ vh
        # rotate k/v to the next device; overlaps with next step's matmul
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, m_new, l_new, acc_new

    _, _, m, l, acc = jax.lax.fori_loop(0, sp, body, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _online_update(m, l, acc, s, vh):
    """One online-softmax block update. s: [B,H,Lq,Lk] UNMASKED scores."""
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    return m_new, l * corr + jnp.sum(p, -1, keepdims=True), \
        acc * corr + p @ vh


def zigzag_ring_attention_local(q, k, v, axis_name="sp", scale=None,
                                use_flash=None):
    """Causal ring attention with the zigzag layout, INSIDE shard_map.

    q,k,v: [B, 2*Lh, H, D] — this shard's two half-chunks, ALREADY in
    zigzag order: rows [:Lh] are global chunk d, rows [Lh:] are global
    chunk 2S-1-d. Output is in the same zigzag order.

    use_flash routes the per-step half-blocks through the Pallas flash
    kernel with lse merging (zigzag_ring_flash_attention_local): balanced
    load AND O(L/sp) memory. Default: on for TPU when the half-chunk
    shape fits the kernel (head-dim tiling, 128-aligned Lh, no GQA)."""
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu"
                     and _flash_ring_ok(q.shape, k.shape[2],
                                        q.shape[1] // 2))
    if use_flash:
        return zigzag_ring_flash_attention_local(q, k, v, axis_name, scale)
    return _zigzag_dense_local(q, k, v, axis_name, scale)


def _zigzag_dense_local(q, k, v, axis_name="sp", scale=None):
    """Dense zigzag step blocks (materializes Lh x Lh scores per block)."""
    sp = _axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale   # [B,H,2Lh,D]
    B, H, L2, D = qh.shape
    Lh = L2 // 2
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # ---- step 0: local causal attention over this shard's own tokens ----
    row = jax.lax.broadcasted_iota(jnp.int32, (L2, L2), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (L2, L2), 1)
    half_of = lambda i: i // Lh                      # 0 -> chunk d, 1 -> 2S-1-d
    pos = lambda i: jnp.where(half_of(i) == 0, d * Lh + i % Lh,
                              (2 * sp - 1 - d) * Lh + i % Lh)
    local_mask = pos(row) >= pos(col)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s0 = jnp.where(local_mask, qh @ jnp.swapaxes(kh, -1, -2), -1e30)
    m = jnp.max(s0, axis=-1, keepdims=True)
    p0 = jnp.where(local_mask, jnp.exp(s0 - m), 0.0)
    l = jnp.sum(p0, -1, keepdims=True)
    acc = p0 @ vh

    m0, m1 = m[..., :Lh, :], m[..., Lh:, :]
    l0, l1 = l[..., :Lh, :], l[..., Lh:, :]
    a0, a1 = acc[..., :Lh, :], acc[..., Lh:, :]
    q0, q1 = qh[..., :Lh, :], qh[..., Lh:, :]

    def body(t, carry):
        k_cur, v_cur, m0, l0, a0, m1, l1, a1 = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (d - t) % sp                   # owner of the visiting shard
        kh = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        kc0, vc0 = kh[..., :Lh, :], vh[..., :Lh, :]   # chunk src
        kc1, vc1 = kh[..., Lh:, :], vh[..., Lh:, :]   # chunk 2S-1-src
        # block X (always needed, fully unmasked): qc1 attends chunk src
        m1, l1, a1 = _online_update(m1, l1, a1, q1 @ jnp.swapaxes(kc0, -1, -2),
                                    vc0)
        # block Y: earlier shard -> qc0 x kc0; later shard -> qc1 x kc1.
        # Gather the target accumulator first so the online update (the
        # expensive p@v matmul + exps) runs ONCE, then scatter back.
        early = src < d
        q_sel = jnp.where(early, q0, q1)
        k_sel = jnp.where(early, kc0, kc1)
        v_sel = jnp.where(early, vc0, vc1)
        m_sel = jnp.where(early, m0, m1)
        l_sel = jnp.where(early, l0, l1)
        a_sel = jnp.where(early, a0, a1)
        s = q_sel @ jnp.swapaxes(k_sel, -1, -2)
        m_new, l_new, a_new = _online_update(m_sel, l_sel, a_sel, s, v_sel)
        m0 = jnp.where(early, m_new, m0)
        l0 = jnp.where(early, l_new, l0)
        a0 = jnp.where(early, a_new, a0)
        m1 = jnp.where(early, m1, m_new)
        l1 = jnp.where(early, l1, l_new)
        a1 = jnp.where(early, a1, a_new)
        return k_cur, v_cur, m0, l0, a0, m1, l1, a1

    _, _, m0, l0, a0, m1, l1, a1 = jax.lax.fori_loop(
        1, sp, body, (k, v, m0, l0, a0, m1, l1, a1))
    out = jnp.concatenate([a0 / jnp.maximum(l0, 1e-30),
                           a1 / jnp.maximum(l1, 1e-30)], axis=2)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _zigzag_perms(sp):
    """ppermute tables moving contiguous layout <-> zigzag layout.

    Contiguous shard e holds half-chunks (2e, 2e+1). Zigzag shard d wants
    (d, 2S-1-d). Each half-chunk c has contiguous owner c//2 and zigzag
    owner (c if c < S else 2S-1-c); one ppermute per half moves them."""
    owner_z = lambda c: c if c < sp else 2 * sp - 1 - c
    to_z_first = [(e, owner_z(2 * e)) for e in range(sp)]
    to_z_second = [(e, owner_z(2 * e + 1)) for e in range(sp)]
    return to_z_first, to_z_second


def _contig_to_zigzag(x, axis_name, sp):
    """[B, 2Lh, ...] contiguous shard -> zigzag shard, inside shard_map."""
    d = jax.lax.axis_index(axis_name)
    Lh = x.shape[1] // 2
    first, second = _zigzag_perms(sp)
    got_a = jax.lax.ppermute(x[:, :Lh], axis_name, first)
    got_b = jax.lax.ppermute(x[:, Lh:], axis_name, second)
    # zigzag shard d receives chunk d (goes to slot 0) and chunk 2S-1-d
    # (slot 1); chunk d arrives via `first` iff d even... both arrivals are
    # disjoint: exactly one of (got_a, got_b) is chunk d, the other 2S-1-d.
    # chunk d has contiguous owner d//2 sending its half (d%2==0 ? first :
    # second); build the slot choice from that parity.
    a_is_low = (d % 2) == 0          # `first` perm carries even chunks
    low = jnp.where(a_is_low, got_a, got_b)
    high = jnp.where(a_is_low, got_b, got_a)
    return jnp.concatenate([low, high], axis=1)


def _zigzag_to_contig(x, axis_name, sp):
    d = jax.lax.axis_index(axis_name)
    Lh = x.shape[1] // 2
    first, second = _zigzag_perms(sp)
    inv_first = [(b, a) for a, b in first]
    inv_second = [(b, a) for a, b in second]
    # zigzag shard d holds chunk d (slot 0) and 2S-1-d (slot 1); route
    # each back to its contiguous owner/half with the inverse perms.
    send_first = jnp.where((d % 2) == 0, x[:, :Lh], x[:, Lh:])
    send_second = jnp.where((d % 2) == 0, x[:, Lh:], x[:, :Lh])
    got_a = jax.lax.ppermute(send_first, axis_name, inv_first)
    got_b = jax.lax.ppermute(send_second, axis_name, inv_second)
    return jnp.concatenate([got_a, got_b], axis=1)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=True,
                   batch_axes=("dp", "fsdp"), scale=None,
                   layout="contiguous", use_flash=None):
    """shard_map wrapper: q,k,v are GLOBAL [B, L, H, D] arrays (or already
    sharded); the sequence dim is split over `axis_name`.

    layout="zigzag" (causal only): re-shards contiguous shards into the
    load-balanced zigzag layout (2 ppermutes of half-shards each way),
    runs zigzag_ring_attention_local, and restores contiguous order —
    ~2x less attention compute at large sp for O(L·D) extra comms.

    use_flash (both layouts): per-ring-step Pallas flash blocks with
    lse-merged partials — O(L/sp) attention memory. None = auto (TPU +
    supported shape; zigzag additionally needs 128-aligned half-chunks).
    """
    from jax.sharding import PartitionSpec as P

    from ..distributed.mesh import compat_shard_map, get_mesh

    mesh = mesh or get_mesh()
    spec = P(batch_axes, axis_name, None, None)
    sp = mesh.shape.get(axis_name, 1)
    if layout == "zigzag" and causal and sp > 1:
        L = q.shape[1]
        if L % (2 * sp) != 0:
            raise ValueError(
                f"ring_attention(layout='zigzag') needs the sequence length "
                f"divisible by 2*sp = {2 * sp} (two half-chunks per shard); "
                f"got L={L} over sp={sp}")
        if use_flash is None:
            use_flash = (jax.default_backend() == "tpu"
                         and _flash_ring_ok(q.shape, k.shape[2],
                                            q.shape[1] // max(2 * sp, 1)))

        def fn(qv, kv, vv, _uf=use_flash):
            qz = _contig_to_zigzag(qv, axis_name, sp)
            kz = _contig_to_zigzag(kv, axis_name, sp)
            vz = _contig_to_zigzag(vv, axis_name, sp)
            oz = zigzag_ring_attention_local(qz, kz, vz,
                                             axis_name=axis_name,
                                             scale=scale, use_flash=_uf)
            return _zigzag_to_contig(oz, axis_name, sp)
        check_vma = not use_flash
    else:
        if use_flash is None:
            use_flash = (jax.default_backend() == "tpu" and sp > 1
                         and _flash_ring_ok(q.shape, k.shape[2],
                                            q.shape[1] // max(sp, 1)))
        fn = functools.partial(ring_attention_local, axis_name=axis_name,
                               causal=causal, scale=scale,
                               use_flash=use_flash)
        # the vma checker can't see through pallas_call's out_shape (same
        # caveat as ulysses.py); keep it active for the dense paths
        check_vma = not use_flash
    return compat_shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check=check_vma)(q, k, v)
