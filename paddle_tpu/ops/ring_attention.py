"""Ring attention: exact flash attention over a sequence-sharded 'sp' axis.

Replaces the reference's sequence-parallel attention (fleet sep/
sparse_attention CUDA paths) with the TPU-native ring algorithm: K/V shards
rotate around the ICI ring via ppermute while each device accumulates its
queries' online-softmax partials — memory O(L/sp), comms overlap with compute.

Used inside shard_map with q/k/v sharded on the sequence dim:
    out = shard_map(partial(ring_attention_local, axis_name="sp", causal=True),
                    mesh, in_specs=P(dp, "sp", None, None), ...)(q, k, v)
Layout: [batch, seq_local, heads, head_dim].
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ring_attention_local", "ring_attention"]


def ring_attention_local(q, k, v, axis_name="sp", causal=True, scale=None):
    """Runs INSIDE shard_map. q,k,v: [B, L_local, H, D] (this shard)."""
    sp = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B,H,Lq,D]
    B, H, Lq, D = qh.shape
    Lk = k.shape[1]

    q_pos = my_idx * Lq + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)

    # derive from qh so the carry inits inherit its varying-axes type
    m0 = jnp.full_like(qh[..., :1], -1e30)
    l0 = jnp.zeros_like(qh[..., :1])
    acc0 = jnp.zeros_like(qh)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(step, carry):
        k_cur, v_cur, m, l, acc = carry
        src = (my_idx - step) % sp  # which shard's k/v we hold this step
        kh = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        s = qh @ jnp.swapaxes(kh, -1, -2)  # [B,H,Lq,Lk]
        if causal:
            k_pos = src * Lk + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + p @ vh
        # rotate k/v to the next device; overlaps with next step's matmul
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, m_new, l_new, acc_new

    _, _, m, l, acc = jax.lax.fori_loop(0, sp, body, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=True,
                   batch_axes=("dp", "fsdp"), scale=None):
    """shard_map wrapper: q,k,v are GLOBAL [B, L, H, D] arrays (or already
    sharded); the sequence dim is split over `axis_name`."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from ..distributed.mesh import get_mesh

    mesh = mesh or get_mesh()
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(ring_attention_local, axis_name=axis_name,
                           causal=causal, scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
