"""One-time fallback signalling for Pallas kernels.

Kernel dispatch keeps a defensive try/except (Pallas lowering support
varies across backends and interpret mode), but abandoning a kernel must
never be silent: a production run quietly using the O(L^2)-HBM reference
path is a perf/memory cliff. Each (kernel, reason) pair warns once.
"""
import warnings

_warned = set()

__all__ = ["kernel_fallback"]


def kernel_fallback(name, err):
    """Record that Pallas kernel `name` was abandoned because of `err`."""
    key = (name, type(err).__name__)
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(
        f"Pallas kernel '{name}' unavailable ({type(err).__name__}: {err}); "
        "falling back to the XLA reference path (slower / more HBM)",
        RuntimeWarning, stacklevel=3)
