"""paddle_tpu.ops — hand-written TPU kernels (Pallas) and their jnp
reference implementations (≈ the reference's paddle/phi/kernels/gpu fused
ops: fused_attention, fused_layer_norm, fused_adam, …)."""
from . import attention  # noqa: F401
from . import fused_ops  # noqa: F401
from . import paged_attention  # noqa: F401
from . import ulysses  # noqa: F401
