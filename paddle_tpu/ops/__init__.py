"""paddle_tpu.ops — hand-written TPU kernels (Pallas) and their jnp
reference implementations (≈ the reference's paddle/phi/kernels/gpu fused
ops: fused_attention, fused_layer_norm, fused_adam, …)."""
from . import attention  # noqa: F401
from . import fused_ops  # noqa: F401
from . import overlap  # noqa: F401
from . import paged_attention  # noqa: F401
from . import ulysses  # noqa: F401

# custom_call target fragments that stay ON DEVICE: Pallas/Mosaic kernel
# calls, GSPMD sharding annotations, and XLA's own device RNG. The Graph
# Doctor's host-transfer analyzer (paddle_tpu.analysis) exempts any
# target containing one of these fragments from host-callback
# classification — today only callback-patterned names are candidates,
# so most entries are future-proofing for a deny-by-default mode; keep
# the list current when adding Pallas kernels with host-ish names.
DEVICE_CUSTOM_CALL_TARGETS = frozenset({
    "tpu_custom_call",          # Mosaic/Pallas TPU kernels
    "mosaic",
    "triton_kernel_call",       # Pallas GPU lowering (parity runs)
    "Sharding",                 # GSPMD annotation, erased by SPMD part.
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
    "cu_threefry2x32",          # device-side RNG
    "LuDecomposition",          # linalg custom calls (lapack on CPU)
    "lapack",
    "ducc_fft",
})
