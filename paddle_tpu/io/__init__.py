"""Data loading — reference python/paddle/io/__init__.py (+ the C++
fluid/operators/reader machinery it fronts).

TPU-native: workers are threads feeding a bounded prefetch queue (XLA releases
the GIL during device compute, so threads overlap host preprocessing with
device steps); batches are optionally device_put ahead of use. A native C++
worker pool (paddle_tpu/runtime) can plug in as the `num_workers` backend.
"""
import itertools
import math
import queue as _queue
import threading

import numpy as np

from ..framework.core import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(total)
    out, ofs = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + n].tolist()))
        ofs += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(p), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (reference
    python/paddle/io/__init__.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.random.RandomState(self.epoch).permutation(n).tolist() \
            if self.shuffle else list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        return self.num_samples // self.batch_size if self.drop_last \
            else math.ceil(self.num_samples / self.batch_size)

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        arrs = [np.asarray(t._value) for t in batch]
        return Tensor(np.stack(arrs))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_map_sync(self):
        for idxs in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_map_threaded(self):
        """Thread pool + bounded queue: overlap host batch assembly with device
        compute (the role of the reference's C++ DoubleBufferReader)."""
        q = _queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        idx_q = _queue.Queue()
        for i, idxs in enumerate(self.batch_sampler):
            idx_q.put((i, idxs))
        n_batches = idx_q.qsize()
        stop = threading.Event()

        def worker(wid):
            _worker_info.info = _WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while not stop.is_set():
                try:
                    i, idxs = idx_q.get_nowait()
                except _queue.Empty:
                    return
                try:
                    q.put((i, self.collate_fn([self.dataset[j] for j in idxs])))
                except Exception as e:  # surface worker errors to the consumer
                    q.put((i, e))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # reorder to sequential batch order
            pending = {}
            next_i = 0
            received = 0
            while received < n_batches:
                i, payload = q.get()
                received += 1
                pending[i] = payload
                while next_i in pending:
                    item = pending.pop(next_i)
                    next_i += 1
                    if isinstance(item, Exception):
                        raise item
                    yield item
        finally:
            stop.set()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers and self.num_workers > 0:
            return self._iter_map_threaded()
        return self._iter_map_sync()
