"""Data loading — reference python/paddle/io/__init__.py (+ the C++
fluid/operators/reader machinery it fronts).

TPU-native: `num_workers > 0` runs PROCESS workers with shared-memory ndarray
transport (reference fluid/dataloader/dataloader_iter.py:341
_DataLoaderIterMultiProcess), so python-bound transforms scale past the GIL.
Workers collate to numpy only — jax is never touched in a forked child — and
the parent wraps batches as Tensors. A thread-pool mode
(`worker_mode="thread"`) remains for transforms that already release the GIL
(numpy, the native image ops in paddle_tpu/runtime/image.py).
"""
import itertools
import math
import multiprocessing as _mp
import queue as _queue
import threading
import traceback as _traceback

from multiprocessing import shared_memory as _shm

import numpy as np

from ..framework.core import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
    "DeviceLoader", "prefetch_to_device", "batch_shardings",
    "PrefetchStats", "prefetch_stats", "reset_prefetch_stats",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(total)
    out, ofs = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + n].tolist()))
        ofs += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(p), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (reference
    python/paddle/io/__init__.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.random.RandomState(self.epoch).permutation(n).tolist() \
            if self.shuffle else list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        return self.num_samples // self.batch_size if self.drop_last \
            else math.ceil(self.num_samples / self.batch_size)

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def _np_collate(batch):
    """Numpy-only collate used inside worker PROCESSES (no jax in children)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(_np_collate([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(t._value) for t in batch])
    return np.asarray(batch) if not isinstance(sample, np.ndarray) \
        else np.stack(batch)


def _tensorize(tree):
    """Parent-side: numpy leaves -> Tensor (matches default_collate_fn)."""
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tensorize(t) for t in tree)
    if isinstance(tree, dict):
        return {k: _tensorize(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        return Tensor(tree)
    return tree


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        arrs = [np.asarray(t._value) for t in batch]
        return Tensor(np.stack(arrs))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    return Tensor(np.asarray(batch))


# ---------------------------------------------------------------------------
# Multiprocess workers (reference _DataLoaderIterMultiProcess): fork'd
# processes, numpy-only collate, shared-memory segments for large arrays.
# ---------------------------------------------------------------------------

_SHM_MIN_BYTES = 4096  # below this, pickling through the queue is cheaper


class _WorkerError:
    def __init__(self, exc):
        self.message = "".join(_traceback.format_exception(
            type(exc), exc, exc.__traceback__))

    def reraise(self):
        raise RuntimeError(f"DataLoader worker failed:\n{self.message}")


def _encode_tree(tree, use_shm):
    """numpy leaves -> ('shm', name, shape, dtype) markers (big arrays) or
    inline values; containers preserved."""
    if isinstance(tree, (list, tuple)):
        return ("__seq__", type(tree).__name__,
                [_encode_tree(t, use_shm) for t in tree])
    if isinstance(tree, dict):
        return ("__map__", {k: _encode_tree(v, use_shm) for k, v in tree.items()})
    if isinstance(tree, np.ndarray) and use_shm and tree.nbytes >= _SHM_MIN_BYTES:
        seg = _shm.SharedMemory(create=True, size=tree.nbytes)
        np.ndarray(tree.shape, tree.dtype, buffer=seg.buf)[...] = tree
        name = seg.name
        seg.close()
        try:
            # ownership transfers to the parent (which unlinks after decode);
            # drop the worker-side tracker registration so its exit doesn't
            # double-clean or warn about "leaked" segments
            from multiprocessing import resource_tracker
            resource_tracker.unregister("/" + name, "shared_memory")
        except Exception:
            pass
        return ("__shm__", name, tree.shape, str(tree.dtype))
    return ("__val__", tree)


def _decode_tree(node):
    tag = node[0]
    if tag == "__seq__":
        items = [_decode_tree(t) for t in node[2]]
        return tuple(items) if node[1] == "tuple" else items
    if tag == "__map__":
        return {k: _decode_tree(v) for k, v in node[1].items()}
    if tag == "__shm__":
        _, name, shape, dtype = node
        seg = _shm.SharedMemory(name=name)
        try:
            arr = np.array(np.ndarray(shape, dtype, buffer=seg.buf))  # copy out
        finally:
            seg.close()
            seg.unlink()
        return arr
    return node[1]


def _release_tree(node):
    """Unlink shm segments of a payload that will never be decoded."""
    tag = node[0]
    if tag == "__seq__":
        for t in node[2]:
            _release_tree(t)
    elif tag == "__map__":
        for v in node[1].values():
            _release_tree(v)
    elif tag == "__shm__":
        try:
            seg = _shm.SharedMemory(name=node[1])
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass


def _mp_map_worker(dataset, collate_fn, index_q, result_q, wid, num_workers,
                   worker_init_fn, use_shm):
    _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn:
        worker_init_fn(wid)
    while True:
        item = index_q.get()
        if item is None:
            return
        epoch, i, idxs = item
        try:
            data = collate_fn([dataset[j] for j in idxs])
            result_q.put((epoch, i, _encode_tree(data, use_shm)))
        except Exception as e:
            result_q.put((epoch, i, _WorkerError(e)))


def _mp_iterable_worker(dataset, collate_fn, batch_size, drop_last, result_q,
                        wid, num_workers, worker_init_fn, use_shm):
    _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn:
        worker_init_fn(wid)
    try:
        batch = []
        for item in dataset:
            batch.append(item)
            if len(batch) == batch_size:
                result_q.put((0, None, _encode_tree(collate_fn(batch), use_shm)))
                batch = []
        if batch and not drop_last:
            result_q.put((0, None, _encode_tree(collate_fn(batch), use_shm)))
    except Exception as e:
        result_q.put((0, None, _WorkerError(e)))
    result_q.put((0, None, "__end__"))


def _poll_result(result_q, user_timeout, check_alive):
    """Blocking result_q.get with worker-liveness polling: a worker that is
    OOM-killed or segfaults mid-batch must raise, not hang the training loop
    (reference _DataLoaderIterMultiProcess watches worker exit the same way)."""
    import time
    deadline = time.monotonic() + user_timeout if user_timeout else None
    while True:
        wait = 5.0
        if deadline is not None:
            wait = min(wait, max(0.01, deadline - time.monotonic()))
        try:
            return result_q.get(timeout=wait)
        except _queue.Empty:
            check_alive()
            if deadline is not None and time.monotonic() >= deadline:
                raise RuntimeError(
                    f"DataLoader timed out after {user_timeout}s waiting on "
                    "worker output")


def _drain_release(result_q):
    """Release shm of any undecoded payloads left in a result queue."""
    while True:
        try:
            _, _, payload = result_q.get_nowait()
        except _queue.Empty:
            return
        except Exception:
            return
        if not isinstance(payload, (_WorkerError, str)):
            _release_tree(payload)


def _start_quiet(procs):
    """Start worker processes, muting the fork-vs-threads warnings: the
    children never touch jax (numpy-only collate), so the JAX/CPython
    fork-with-threads caveat does not apply to them."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        warnings.simplefilter("ignore", DeprecationWarning)
        for p in procs:
            p.start()


class _MultiprocessPool:
    """Worker processes + queues, reusable across epochs (persistent_workers)."""

    def __init__(self, loader):
        ctx = _mp.get_context("fork" if "fork" in _mp.get_all_start_methods()
                              else "spawn")
        self.loader = loader
        self.epoch = 0
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        collate = loader.collate_fn
        if collate is default_collate_fn:
            collate = _np_collate  # never touch jax inside a forked child
        self.procs = [
            ctx.Process(
                target=_mp_map_worker,
                args=(loader.dataset, collate, self.index_q, self.result_q,
                      w, loader.num_workers, loader.worker_init_fn,
                      loader.use_shared_memory),
                daemon=True)
            for w in range(loader.num_workers)]
        _start_quiet(self.procs)

    def shutdown(self):
        for _ in self.procs:
            try:
                self.index_q.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        # release shm of any results the consumer never decoded
        _drain_release(self.result_q)

    def _check_workers_alive(self):
        dead = [p for p in self.procs if not p.is_alive()]
        if dead:
            codes = [p.exitcode for p in dead]
            raise RuntimeError(
                f"{len(dead)} DataLoader worker(s) exited unexpectedly "
                f"(exit codes {codes}) — e.g. OOM-killed or segfaulted in "
                "dataset.__getitem__")

    def run_epoch(self):
        loader = self.loader
        self.epoch += 1
        epoch = self.epoch
        batches = list(loader.batch_sampler)
        n = len(batches)
        depth = min(n, loader.num_workers * loader.prefetch_factor)
        for j in range(depth):
            self.index_q.put((epoch, j, batches[j]))
        sent = depth
        pending, next_i, received = {}, 0, 0
        try:
            while received < n:
                payload = _poll_result(self.result_q, loader.timeout,
                                       self._check_workers_alive)
                ep, i, payload = payload
                if ep != epoch:       # stale result from an abandoned epoch
                    if not isinstance(payload, (_WorkerError, str)):
                        _release_tree(payload)
                    continue
                received += 1
                if sent < n:
                    self.index_q.put((epoch, sent, batches[sent]))
                    sent += 1
                pending[i] = payload
                while next_i in pending:
                    payload = pending.pop(next_i)
                    next_i += 1
                    if isinstance(payload, _WorkerError):
                        payload.reraise()
                    yield _tensorize(_decode_tree(payload))
        finally:
            # error or early consumer break: release out-of-order results we
            # already popped; in-flight queue results drain on the next epoch
            # (epoch tag) or in shutdown()
            for payload in pending.values():
                if not isinstance(payload, (_WorkerError, str)):
                    _release_tree(payload)
            if not loader.persistent_workers:
                self.shutdown()


class _GeneratorLoader:
    """Loader returned by DataLoader.from_generator (the deprecated
    fluid feeder, reference fluid/reader.py): old migration code calls
    one of the set_*_generator methods and then iterates. Batches pass
    through as tensors; sample generators are batched with the given
    batch_size."""

    def __init__(self, return_list=False, drop_last=True, capacity=None,
                 use_double_buffer=True):
        if not return_list:
            # reference DygraphGeneratorLoader (fluid/reader.py:967-971)
            # warns and coerces to list mode — dict-of-feed-name batches
            # are a static-graph-only behavior
            import warnings
            warnings.warn(
                "Please NOTE: DygraphGeneratorLoader supports returning "
                "as list only. Change to return as list.")
        self._gen = None
        self._mode = "batch"
        self._batch_size = 1
        self._drop_last = drop_last
        # reference from_generator(capacity, use_double_buffer) fed the C++
        # DoubleBufferReader; here they parameterize the thread prefetcher
        # (io.prefetch): capacity = queue depth, use_double_buffer = on/off
        self._capacity = capacity
        self._use_double_buffer = use_double_buffer

    def set_batch_generator(self, generator, places=None):
        self._gen, self._mode = generator, "batch"
        return self

    def set_sample_list_generator(self, generator, places=None):
        # each yielded item is a LIST OF SAMPLES -> collate to batch
        # tensors (reference fluid reader semantics)
        self._gen, self._mode = generator, "sample_list"
        return self

    def set_sample_generator(self, generator, batch_size=1, drop_last=None,
                             places=None):
        self._gen, self._mode = generator, "sample"
        self._batch_size = batch_size
        if drop_last is not None:   # else keep from_generator's setting
            self._drop_last = drop_last
        return self

    def _batches(self):
        if self._mode == "batch":
            for item in self._gen():
                yield _to_tensor_tree(item)
            return
        if self._mode == "sample_list":
            for samples in self._gen():
                yield default_collate_fn(list(samples))
            return
        buf = []
        for sample in self._gen():
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield default_collate_fn(buf)
                buf = []
        if buf and not self._drop_last:
            yield default_collate_fn(buf)

    def __iter__(self):
        if self._gen is None:
            raise RuntimeError("call set_batch_generator / "
                               "set_sample_generator first")
        if self._use_double_buffer and self._capacity:
            # batch assembly runs in a background thread, `capacity` deep;
            # generator errors re-raise at next() with the worker traceback
            from .prefetch import prefetch_iterator
            return prefetch_iterator(self._batches(), depth=self._capacity)
        return self._batches()


def _to_tensor_tree(item):
    if isinstance(item, (list, tuple)):
        return type(item)(_to_tensor_tree(v) for v in item)
    if isinstance(item, dict):
        return {k: _to_tensor_tree(v) for k, v in item.items()}
    if isinstance(item, Tensor) or np.isscalar(item):
        return item
    import jax.numpy as jnp
    try:
        return Tensor(jnp.asarray(item))
    except TypeError:
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 worker_mode="process"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        self.worker_mode = worker_mode  # "process" (reference parity) | "thread"
        self._pool = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_iterable_multiprocess(self):
        """Each worker iterates its own copy of the dataset (shard inside
        __iter__ via get_worker_info, reference semantics); batches arrive
        worker-interleaved."""
        ctx = _mp.get_context("fork" if "fork" in _mp.get_all_start_methods()
                              else "spawn")
        result_q = ctx.Queue()
        collate = self.collate_fn
        if collate is default_collate_fn:
            collate = _np_collate
        procs = [ctx.Process(
            target=_mp_iterable_worker,
            args=(self.dataset, collate, self.batch_size, self.drop_last,
                  result_q, w, self.num_workers, self.worker_init_fn,
                  self.use_shared_memory), daemon=True)
            for w in range(self.num_workers)]
        _start_quiet(procs)
        live = len(procs)

        def check_alive():
            if any(not p.is_alive() and p.exitcode not in (0, None)
                   for p in procs):
                raise RuntimeError(
                    "a DataLoader iterable worker exited unexpectedly")

        try:
            while live:
                _, _, payload = _poll_result(result_q, self.timeout, check_alive)
                if payload == "__end__":
                    live -= 1
                    continue
                if isinstance(payload, _WorkerError):
                    payload.reraise()
                yield _tensorize(_decode_tree(payload))
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            _drain_release(result_q)

    def _iter_map_sync(self):
        for idxs in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_map_threaded(self):
        """Thread pool + bounded queue: overlap host batch assembly with device
        compute (the role of the reference's C++ DoubleBufferReader).

        Index batches are pulled LAZILY from the sampler under a lock — a
        huge epoch never materializes its whole index list up front — and
        completion is tracked by per-worker done markers instead of
        `Queue.qsize` (approximate on some platforms)."""
        q = _queue.Queue(maxsize=max(1, self.num_workers * self.prefetch_factor))
        src = enumerate(iter(self.batch_sampler))
        src_lock = threading.Lock()
        stop = threading.Event()
        done_marker = object()

        def pull():
            with src_lock:
                return next(src, None)

        def put(payload):
            # stop-aware bounded put: a consumer that breaks early must not
            # strand workers blocked on a full queue
            while not stop.is_set():
                try:
                    q.put(payload, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def worker(wid):
            # the done marker is put UNCONDITIONALLY (finally): a worker
            # dying in worker_init_fn or in the user sampler's iterator
            # must not leave the consumer blocked on q.get() forever —
            # those errors travel as an index-less (None, exc) payload
            try:
                _worker_info.info = _WorkerInfo(wid, self.num_workers,
                                                self.dataset)
                if self.worker_init_fn:
                    self.worker_init_fn(wid)
                while not stop.is_set():
                    item = pull()
                    if item is None:
                        break
                    i, idxs = item
                    try:
                        payload = (i, self.collate_fn(
                            [self.dataset[j] for j in idxs]))
                    except Exception as e:  # surface errors to the consumer
                        payload = (i, e)
                    if not put(payload):
                        return
            except Exception as e:      # init / sampler failure
                put((None, e))
            finally:
                put(done_marker)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # reorder to sequential batch order
            pending = {}
            next_i = 0
            done = 0
            while done < len(threads) or pending:
                item = q.get()
                if item is done_marker:
                    done += 1
                    continue
                i, payload = item
                if i is None:           # worker died outside a batch
                    raise payload
                pending[i] = payload
                while next_i in pending:
                    item = pending.pop(next_i)
                    next_i += 1
                    if isinstance(item, Exception):
                        raise item
                    yield item
        finally:
            stop.set()
            while True:   # unblock workers parked on a full queue
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            for t in threads:
                t.join(timeout=5)

    def _iter_map_multiprocess(self):
        # pool is created lazily HERE (inside the generator) so that an
        # iterator that is never advanced doesn't strand worker processes
        if self._pool is None or not self.persistent_workers:
            self._pool = _MultiprocessPool(self)
        yield from self._pool.run_epoch()

    def __iter__(self):
        if self._iterable_mode:
            if self.num_workers and self.num_workers > 0:
                return self._iter_iterable_multiprocess()
            return self._iter_iterable()
        if self.num_workers and self.num_workers > 0:
            if self.worker_mode == "thread":
                return self._iter_map_threaded()
            return self._iter_map_multiprocess()
        return self._iter_map_sync()

    def __del__(self):
        pool, self._pool = self._pool, None
        if pool is not None and self.persistent_workers:
            try:
                pool.shutdown()
            except Exception:
                pass

    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        """Deprecated fluid feeder (reference fluid/reader.py:570
        from_generator, default return_list=False): returns a loader
        whose set_*_generator methods install a python generator. Like
        the reference dygraph loader, return_list=False warns and
        coerces to list mode; new code should construct
        DataLoader(dataset) directly.

        `capacity`/`use_double_buffer` map onto the thread prefetcher
        (io.prefetch): with a capacity given and double buffering on
        (the reference default), batches are assembled `capacity` ahead
        in a background thread. Device placement belongs to
        `io.DeviceLoader`, which new code should use instead."""
        return _GeneratorLoader(return_list=return_list,
                                drop_last=drop_last, capacity=capacity,
                                use_double_buffer=use_double_buffer)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Reference from_dataset feeds the C++ parameter-server Dataset
        (fleet PS mode). The TPU-native answer to that workload is
        mesh-sharded embedding tables (distributed.ShardedEmbedding) +
        a normal DataLoader — see docs/distributed.md."""
        raise NotImplementedError(
            "from_dataset wraps the fluid parameter-server Dataset; use "
            "DataLoader(dataset) with distributed.ShardedEmbedding for "
            "recsys-scale tables (docs/distributed.md)")


from .prefetch import (  # noqa: E402  (DataLoader must exist first)
    DeviceLoader, PrefetchStats, batch_shardings, prefetch_stats,
    prefetch_to_device, reset_prefetch_stats)
