"""Async device input pipeline — sharded batch prefetch.

The Trainer compiles ONE XLA program per step; the paper's thesis only
holds if that program is fully fed. A synchronous feed breaks it twice
per step: the H2D copy of the batch sits on the dispatch path, and the
batch arrives replicated (or host-resident) so GSPMD reshards it inside
the step. `DeviceLoader` is the reference DoubleBufferReader rebuilt for
the mesh era: a background thread pulls batches from any
DataLoader/iterator and keeps `depth` upcoming batches resident on the
mesh with the GSPMD batch sharding (leading dim over the data axes,
`distributed.trainer.shard_batch` semantics) via `jax.device_put` — an
async enqueue, so the copy of batch N+1 overlaps step N's compute.

Telemetry rides along (`PrefetchStats` / `prefetch_stats()`): batches
prefetched, queue depth, and host time blocked waiting on input, so the
overlap is observable rather than asserted — if `time_blocked_on_input_s`
dominates, the pipeline (not the chip) is the bottleneck.
"""
import queue as _queue
import threading
import time
import traceback as _traceback
import weakref

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["DeviceLoader", "prefetch_to_device", "batch_shardings",
           "batch_signature", "prefetch_iterator", "PrefetchStats",
           "prefetch_stats", "reset_prefetch_stats", "stack_batches",
           "stack_leaf_values", "horizon_shardings"]


def batch_signature(arrays):
    """Cache key for a batch pytree: (treedef, ((shape, dtype), ...)).
    dtypes are canonicalized (int64 numpy and the int32 device array it
    becomes under disabled x64 must hit the same entry). NEVER touches
    the data — `np.asarray` only for leaves with no `.dtype` (python
    scalars); a device array must not be fetched just to read its dtype."""
    leaves, treedef = jax.tree_util.tree_flatten(arrays)
    sig = []
    for v in leaves:
        dt = getattr(v, "dtype", None)
        if dt is None:
            dt = np.asarray(v).dtype
        sig.append((np.shape(v), str(jax.dtypes.canonicalize_dtype(dt))))
    return (treedef, tuple(sig))

_END = object()      # producer-side end-of-stream marker


class _PrefetchError:
    """Worker-thread failure, re-raised at the consumer's next() with the
    original traceback (same contract as the process workers'
    ``_WorkerError.reraise``)."""

    def __init__(self, exc):
        self.message = "".join(_traceback.format_exception(
            type(exc), exc, exc.__traceback__))

    def reraise(self):
        raise RuntimeError(f"prefetch worker failed:\n{self.message}")


class PrefetchStats:
    """Per-loader input-pipeline telemetry."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.batches = 0            # batches handed to the consumer
        self.epochs = 0             # __iter__ calls
        self.put_time_s = 0.0       # host time spent enqueueing H2D copies
        self.blocked_time_s = 0.0   # host time blocked waiting on input
        self.queue_depth = 0        # depth observed at the last next()
        self.max_queue_depth = 0

    def snapshot(self):
        return {"batches_prefetched": self.batches,
                "epochs": self.epochs,
                "h2d_put_time_s": round(self.put_time_s, 6),
                "time_blocked_on_input_s": round(self.blocked_time_s, 6),
                "queue_depth": self.queue_depth,
                "max_queue_depth": self.max_queue_depth}


_STATS_REGISTRY = []   # weakrefs; aggregate view for debug telemetry


def _register_stats(stats):
    _STATS_REGISTRY.append(weakref.ref(stats))


def prefetch_stats():
    """Aggregate snapshot over every live prefetcher (the
    `debug.input_pipeline_stats()` backend)."""
    agg = PrefetchStats().snapshot()
    live = []
    for ref in _STATS_REGISTRY:
        s = ref()
        if s is None:
            continue
        live.append(ref)
        snap = s.snapshot()
        for k in ("batches_prefetched", "epochs", "h2d_put_time_s",
                  "time_blocked_on_input_s"):
            agg[k] = round(agg[k] + snap[k], 6)
        agg["queue_depth"] += snap["queue_depth"]
        agg["max_queue_depth"] = max(agg["max_queue_depth"],
                                     snap["max_queue_depth"])
    _STATS_REGISTRY[:] = live
    return agg


def reset_prefetch_stats():
    for ref in _STATS_REGISTRY:
        s = ref()
        if s is not None:
            s.reset()
    _STATS_REGISTRY[:] = [r for r in _STATS_REGISTRY if r() is not None]


def _leaf_array(v):
    """Batch leaf -> raw array WITHOUT copying device arrays back to host."""
    from ..framework.core import Tensor
    if isinstance(v, Tensor):
        return v._value
    if isinstance(v, (jax.Array, np.ndarray)):
        return v
    return np.asarray(v)


def _leaf_arrays(tree):
    from ..framework.core import Tensor
    return jax.tree_util.tree_map(
        _leaf_array, tree, is_leaf=lambda x: isinstance(x, Tensor))


def batch_shardings(batch, mesh=None, spec=("dp", "fsdp")):
    """NamedSharding pytree for a batch: leading dim over the data axes,
    everything else replicated (`distributed.trainer.shard_batch`
    placement). Axes that don't divide the batch dim are dropped
    (feasible_spec policy) so user-sized batches degrade to replication
    instead of raising. Computed from SHAPES only, so the result can be
    cached and passed as jit ``in_shardings``."""
    from ..distributed.mesh import get_mesh
    from ..distributed.sharding_utils import feasible_spec
    from ..framework.core import Tensor
    mesh = mesh or get_mesh()
    spec = tuple(spec)

    def sh(v):
        shape = np.shape(v._value) if isinstance(v, Tensor) else np.shape(v)
        if not shape:
            return NamedSharding(mesh, PartitionSpec())
        fspec = feasible_spec(shape, (spec,) + (None,) * (len(shape) - 1),
                              mesh)
        return NamedSharding(mesh, PartitionSpec(*fspec))

    return jax.tree_util.tree_map(sh, batch,
                                  is_leaf=lambda x: isinstance(x, Tensor))


def stack_leaf_values(leaves):
    """[per-step leaf, ...] -> one [N, ...] array — THE leaf-stacking
    policy for training horizons, shared by `stack_batches` and
    `hapi.Model`'s fit grouping: host leaves stack with numpy (no
    device work); device-resident leaves stack with jnp — a device-side
    concat dispatch, never a D2H fetch."""
    if any(isinstance(v, jax.Array) for v in leaves):
        import jax.numpy as jnp
        return jnp.stack(leaves)
    return np.stack([np.asarray(v) for v in leaves])


def stack_batches(batches):
    """[batch pytree, ...] -> ONE pytree with each leaf leading-stacked
    to [N, ...] (the `Trainer.step_multi` horizon layout)."""
    trees = [_leaf_arrays(b) for b in batches]
    return jax.tree_util.tree_map(
        lambda *leaves: stack_leaf_values(leaves), *trees)


def horizon_shardings(batch, mesh=None, spec=("dp", "fsdp")):
    """NamedSharding pytree for a leading-STACKED horizon batch
    ([N, B, ...] leaves): the scan dim replicated (every device runs
    every tick), the per-step batch dim sharded over the data axes —
    `batch_shardings` shifted one dim right. Shapes-only, cacheable,
    usable as the fused scan's jit in_shardings."""
    from ..distributed.mesh import get_mesh
    from ..distributed.sharding_utils import feasible_spec
    from ..framework.core import Tensor
    mesh = mesh or get_mesh()
    spec = tuple(spec)

    def sh(v):
        shape = np.shape(v._value) if isinstance(v, Tensor) else np.shape(v)
        if len(shape) < 2:
            return NamedSharding(mesh, PartitionSpec())
        fspec = feasible_spec(shape[1:],
                              (spec,) + (None,) * (len(shape) - 2), mesh)
        return NamedSharding(mesh, PartitionSpec(None, *fspec))

    return jax.tree_util.tree_map(sh, batch,
                                  is_leaf=lambda x: isinstance(x, Tensor))


class _PrefetchIterator:
    """Bounded-queue background producer. `transform` runs IN the worker
    thread (this is where DeviceLoader's device_put goes — off the
    consumer's critical path); errors re-raise at next(); close() joins
    the thread."""

    def __init__(self, source, depth, transform=None, stats=None):
        self._q = _queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._stats = stats
        self._exhausted = False
        self._thread = threading.Thread(
            target=self._producer, args=(source, transform),
            daemon=True, name="paddle_tpu-prefetch")
        self._thread.start()

    # -- producer side ------------------------------------------------------
    def _producer(self, source, transform):
        try:
            for item in source:
                if self._stop.is_set():
                    return
                if transform is not None:
                    item = transform(item)
                if not self._put(item):
                    return
            self._put(_END)
        except BaseException as e:   # re-raised at the consumer's next()
            self._put(_PrefetchError(e))

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                if self._stats is not None:
                    self._stats.max_queue_depth = max(
                        self._stats.max_queue_depth, self._q.qsize())
                return True
            except _queue.Full:
                continue
        return False

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        t0 = time.monotonic()
        item = self._q.get()
        if self._stats is not None:
            self._stats.blocked_time_s += time.monotonic() - t0
        if item is _END:
            self._exhausted = True
            self._thread.join(timeout=5)
            raise StopIteration
        if isinstance(item, _PrefetchError):
            self._exhausted = True
            self._thread.join(timeout=5)
            item.reraise()
        if self._stats is not None:
            self._stats.batches += 1
            self._stats.queue_depth = self._q.qsize()
        return item

    def close(self):
        """Stop the producer and join its thread (no leak even when the
        consumer breaks mid-epoch). Idempotent."""
        self._stop.set()
        self._exhausted = True
        while True:   # unblock a producer waiting on a full queue
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break
        self._thread.join(timeout=5)
        return not self._thread.is_alive()

    def __del__(self):
        try:
            if not self._exhausted:
                self.close()
        except Exception:
            pass


def prefetch_iterator(source, depth=2, transform=None, stats=None):
    """Host-side prefetch: background thread + bounded queue over any
    iterable, no device placement. Backs `DataLoader.from_generator`'s
    `use_double_buffer`/`capacity` flags."""
    return _PrefetchIterator(iter(source), depth, transform, stats)


class DeviceLoader:
    """Wrap any DataLoader/iterable; yield mesh-resident, GSPMD-sharded
    batches, keeping `depth` batches in flight.

        loader = io.DataLoader(dataset, batch_size=128, num_workers=4)
        for batch in io.DeviceLoader(loader, depth=2):
            loss = trainer.step(batch)       # zero H2D on the step path

    Leaves arrive as committed jax Arrays sharded over `spec` on their
    leading dim (axes that don't divide are dropped), exactly the layout
    `Trainer` pins as its batch `in_shardings` — so the step dispatches
    with no copy and no reshard. Sharding pytrees are computed once per
    (structure, shapes, dtypes) signature and reused."""

    def __init__(self, loader, mesh=None, depth=2, spec=("dp", "fsdp")):
        self.loader = loader
        self.depth = max(1, int(depth))
        self.spec = tuple(spec)
        self._mesh = mesh
        self.stats = PrefetchStats()
        _register_stats(self.stats)
        self._sharding_cache = {}
        self._live = []   # weakrefs to iterators, for close()

    @property
    def mesh(self):
        if self._mesh is None:
            from ..distributed.mesh import get_mesh
            self._mesh = get_mesh()
        return self._mesh

    def _shardings_for(self, arrays):
        key = batch_signature(arrays)
        sh = self._sharding_cache.get(key)
        if sh is None:
            sh = batch_shardings(arrays, self.mesh, self.spec)
            self._sharding_cache[key] = sh
        return sh

    def _place(self, batch):
        """Runs in the prefetch thread: async H2D enqueue off the step
        path. device_put on an already-matching array is a no-op."""
        arrays = _leaf_arrays(batch)
        t0 = time.monotonic()
        out = jax.device_put(arrays, self._shardings_for(arrays))
        self.stats.put_time_s += time.monotonic() - t0
        return out

    def __iter__(self):
        self.stats.epochs += 1
        it = _PrefetchIterator(iter(self.loader), self.depth,
                               transform=self._place, stats=self.stats)
        self._live = [r for r in self._live if r() is not None]
        self._live.append(weakref.ref(it))
        return it

    # -- horizon feed (Trainer.step_multi) -----------------------------------

    def _horizon_shardings_for(self, arrays):
        key = ("horizon", batch_signature(arrays))
        sh = self._sharding_cache.get(key)
        if sh is None:
            sh = horizon_shardings(arrays, self.mesh, self.spec)
            self._sharding_cache[key] = sh
        return sh

    def _place_stack(self, group):
        """Runs in the prefetch thread: stack `n` source batches into
        one [n, ...] pytree and enqueue the H2D copy — the stack happens
        BEFORE placement (host numpy, np.stack; already-resident leaves
        jnp.stack on device), so feeding a horizon costs zero host
        round-trips on the step path."""
        arrays = stack_batches(group)
        t0 = time.monotonic()
        out = jax.device_put(arrays, self._horizon_shardings_for(arrays))
        self.stats.put_time_s += time.monotonic() - t0
        return out

    def stack(self, n):
        """Horizon feed: iterate mesh-resident batches stacked `n` deep
        ([n, B, ...] leaves, scan dim replicated, batch dim sharded) —
        exactly the layout `Trainer.step_multi` pins as its batch
        in_shardings, so the fused N-step scan dispatches with no copy
        and no reshard:

            for horizon in loader.stack(8):
                losses.append(trainer.step_multi(horizon))   # 1 dispatch

        The final partial group (epoch length not a multiple of n)
        yields with leading m < n — callers fall back to per-step for
        it (`Model.fit` does). Counts one prefetched batch per horizon
        in the stats."""
        n = max(1, int(n))
        self.stats.epochs += 1
        source = iter(self.loader)

        def groups():
            group = []
            for item in source:
                group.append(item)
                if len(group) == n:
                    yield group
                    group = []
            if group:
                yield group

        it = _PrefetchIterator(groups(), self.depth,
                               transform=self._place_stack,
                               stats=self.stats)
        self._live = [r for r in self._live if r() is not None]
        self._live.append(weakref.ref(it))
        return it

    def __len__(self):
        return len(self.loader)

    def close(self):
        """Close every live iterator (join prefetch threads)."""
        for ref in self._live:
            it = ref()
            if it is not None:
                it.close()
        self._live = []


def prefetch_to_device(iterator, depth=2, mesh=None, spec=("dp", "fsdp")):
    """Functional face of DeviceLoader: wrap an iterator/generator and get
    an iterator of device-resident sharded batches (works with `next()`,
    e.g. over an infinite synthetic-batch generator)."""
    return iter(DeviceLoader(iterator, mesh=mesh, depth=depth, spec=spec))
