"""Optimizers — reference python/paddle/optimizer/*.py.

Every optimizer defines one pure per-parameter update rule. Two consumption
modes share it:

  eager (paddle UX):  loss.backward(); opt.step(); opt.clear_grad()
  compiled (TPU path): state = opt.init_state_pytree(params)
                       params, state = opt.apply_gradients_pytree(params, grads, state, lr)
    — called inside jax.jit/value_and_grad train steps; with GSPMD-sharded
    params the slots inherit the param sharding (ZeRO-style when params are
    sharded over 'fsdp').

multi_precision keeps an fp32 master copy for bf16/fp16 params (reference
adamw multi_precision flag).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from ..nn.clip import ClipGradBase
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb"]


def _is_low_precision(d):
    return jnp.dtype(d) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))


class Optimizer:
    _slot_names = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None,
                 accumulator_dtype=None):
        self._parameter_list = list(parameters) if parameters is not None else None
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # TPU HBM saver: keep moment slots in bf16 (compute stays fp32).
        # Halves Adam state for 1B+ models on a 16GB chip.
        self._acc_dtype = jnp.dtype(accumulator_dtype) if accumulator_dtype else None
        if weight_decay is None:
            self._wd = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._wd = float(weight_decay)
        else:  # L2Decay object
            self._wd = float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))
        self._accumulators = {}
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate, LRScheduler) else None

    # -- update rule (override) ---------------------------------------------
    def _init_slot(self, name, p_value):
        return jnp.zeros_like(p_value, dtype=self._acc_dtype or jnp.float32)

    def _slots_to_f32(self, slots):
        return {k: v.astype(jnp.float32) for k, v in slots.items()}

    def _slots_from_f32(self, slots):
        if self._acc_dtype is None:
            return slots
        return {k: v.astype(self._acc_dtype) for k, v in slots.items()}

    def _update_rule(self, p, g, slots, lr, step):
        """Returns (new_p, new_slots). p/g are fp32 here (master weights)."""
        raise NotImplementedError

    def _decoupled_wd(self):
        return False

    # -- eager path ----------------------------------------------------------
    def _ensure_slots(self, pid, p):
        if pid not in self._accumulators:
            base = p._value.astype(jnp.float32) if self._multi_precision or True else p._value
            slots = {name: self._init_slot(name, base) for name in self._slot_names}
            if self._multi_precision and _is_low_precision(p.dtype):
                slots["master"] = p._value.astype(jnp.float32)
            self._accumulators[pid] = slots
        return self._accumulators[pid]

    @jax.named_scope("optimizer_step")
    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._apply_param_updates(params_grads, self.get_lr())

    def apply_gradients(self, params_grads):
        """Reference optimizer.apply_gradients — the second half of
        minimize: apply THIS optimizer's update rule to explicit
        (param, grad) pairs. The optimizer's grad_clip applies, exactly
        as in step()."""
        self._step_count += 1
        pairs = [(p, g if isinstance(g, Tensor) else Tensor(jnp.asarray(g)))
                 for p, g in params_grads]
        if self._grad_clip is not None:
            pairs = self._grad_clip(pairs)
        self._apply_param_updates(pairs, self.get_lr())

    def backward(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None, callbacks=None):
        """Reference optimizer.backward — the first half of minimize:
        run autograd and return the (param, grad) pairs this optimizer
        would update."""
        loss.backward()
        plist = parameters if parameters is not None else self._parameter_list
        return [(p, p.grad) for p in plist
                if not p.stop_gradient and p.grad is not None]

    def _apply_param_updates(self, params_grads, lr):
        for p, g in params_grads:
            slots = self._ensure_slots(id(p), p)
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) else lr
            master = slots.get("master")
            pv = master if master is not None else p._value.astype(jnp.float32)
            gv = g._value.astype(jnp.float32)
            rs = getattr(self, "_rescale_grad", 1.0)
            if rs != 1.0:
                # reference kernels rescale the RAW gradient, then add
                # the decay term — the decay coefficient must not scale
                gv = gv * rs
            if self._wd and not self._decoupled_wd() and p.regularizer is None:
                gv = gv + self._wd * pv
            rule_slots = self._slots_to_f32({k: v for k, v in slots.items() if k != "master"})
            self._current_param_name = getattr(p, "name", None)
            self._current_param_obj = p
            self._last_lazy_mask = None
            new_p, new_slots = self._update_rule(pv, gv, rule_slots, p_lr, self._step_count)
            new_slots = self._slots_from_f32(new_slots)
            if self._wd and self._decoupled_wd():
                decay = p_lr * self._wd * pv
                if getattr(self, "_last_lazy_mask", None) is not None:
                    decay = decay * self._last_lazy_mask
                new_p = new_p - decay
            if master is not None:
                slots["master"] = new_p
            slots.update(new_slots)
            p._value = new_p.astype(p.dtype)

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..framework.core import SymbolicVar
        if isinstance(loss, SymbolicVar):
            # static mode: register a train spec; Executor.run differentiates
            # the fetched graph and applies this optimizer's update.
            from .. import static
            static._register_minimize(loss, self)
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    # -- pure/functional path -------------------------------------------------
    def init_state_pytree(self, params):
        """params: {name: array} → state pytree (dict of slot dicts)."""
        state = {}
        for name, v in params.items():
            v32 = v.astype(jnp.float32)
            slots = {s: self._init_slot(s, v32) for s in self._slot_names}
            if self._multi_precision and _is_low_precision(v.dtype):
                slots["master"] = v32
            state[name] = slots
        return {"slots": state, "step": jnp.zeros((), jnp.int32)}

    def apply_gradients_pytree(self, params, grads, state, lr=None):
        lr = self.get_lr() if lr is None else lr
        step = state["step"] + 1
        if self._grad_clip is not None:
            grads = self._grad_clip.clip_pytree(grads)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name) if isinstance(grads, dict) else grads[name]
            if g is None:
                new_params[name] = p
                new_state[name] = state["slots"][name]
                continue
            slots = dict(state["slots"][name])
            master = slots.pop("master", None)
            pv = master if master is not None else p.astype(jnp.float32)
            gv = g.astype(jnp.float32)
            rs = getattr(self, "_rescale_grad", 1.0)
            if rs != 1.0:
                gv = gv * rs
            if self._wd and not self._decoupled_wd():
                gv = gv + self._wd * pv
            self._current_param_name = name
            self._current_param_obj = None
            self._last_lazy_mask = None
            new_p, new_slots = self._update_rule(pv, gv, self._slots_to_f32(slots), lr, step)
            new_slots = self._slots_from_f32(new_slots)
            if self._wd and self._decoupled_wd():
                decay = lr * self._wd * pv
                if getattr(self, "_last_lazy_mask", None) is not None:
                    decay = decay * self._last_lazy_mask
                new_p = new_p - decay
            out_slots = dict(new_slots)
            if master is not None:
                out_slots["master"] = new_p
            new_params[name] = new_p.astype(p.dtype)
            new_state[name] = out_slots
        return new_params, {"slots": new_state, "step": step}

    # -- checkpointing --------------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        for i, p in enumerate(self._parameter_list or []):
            slots = self._accumulators.get(id(p), {})
            for k, v in slots.items():
                out[f"{p.name or i}.{k}"] = Tensor(v)
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("step", 0))
        for i, p in enumerate(self._parameter_list or []):
            slots = {}
            for k in self._slot_names + (("master",) if self._multi_precision else ()):
                key = f"{p.name or i}.{k}"
                if key in state_dict:
                    v = state_dict[key]
                    slots[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if slots:
                self._accumulators[id(p)] = slots
        if "LR_Scheduler" in state_dict and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])


class SGD(Optimizer):
    def _update_rule(self, p, g, slots, lr, step):
        return p - lr * g, {}


class Momentum(Optimizer):
    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0,
                 use_multi_tensor=False, name=None):
        # use_multi_tensor is the reference's fused CUDA multi-tensor
        # apply — accepted for parity, meaningless here: the whole train
        # step already compiles to one XLA program
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._rescale_grad = float(rescale_grad)

    def _update_rule(self, p, g, slots, lr, step):
        # rescale_grad is applied by the base class BEFORE weight decay
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class LarsMomentum(Optimizer):
    """LARS: layer-adaptive rate scaling for large-batch training (reference
    python/paddle/fluid/optimizer.py:1964 LarsMomentumOptimizer, surfaced by
    fleet's lars meta_optimizer
    python/paddle/distributed/fleet/meta_optimizers/lars_optimizer.py:21).

        local_lr = lr * lars_coeff * ||p|| / (||g|| + lars_wd * ||p|| + eps)
        v = mu * v + local_lr * (g + lars_wd * p)
        p = p - v
    """

    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._exclude = tuple(exclude_from_weight_decay or ())
        self._epsilon = epsilon
        self._rescale = rescale_grad

    def _wd_for(self):
        # the caller loops set _current_param_name (Parameter.name in the
        # eager path, the pytree key in the functional path) — static python
        # strings, so this specializes per-param at trace time
        name = getattr(self, "_current_param_name", None) or ""
        if any(tok in name for tok in self._exclude):
            return 0.0
        return self._lars_wd

    def _update_rule(self, p, g, slots, lr, step):
        wd = self._wd_for()
        g = g * self._rescale
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        denom = g_norm + wd * p_norm + self._epsilon
        # reference kernel scales only when BOTH norms are nonzero, else
        # plain lr — otherwise zero-init params (every Linear bias) would get
        # local_lr = 0 and never train
        local_lr = jnp.where((p_norm > 0.0) & (g_norm > 0.0),
                             lr * self._lars_coeff * p_norm / jnp.maximum(denom, 1e-30),
                             lr)
        v = self._momentum * slots["velocity"] + local_lr * (g32 + wd * p32)
        new_p = (p32 - v).astype(p.dtype)
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None,
                 accumulator_dtype=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, accumulator_dtype=accumulator_dtype)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # reference lazy_mode updates only rows present in the sparse
        # (SelectedRows) gradient — i.e. it only affects Embedding(
        # sparse=True) weights; dense params behave normally. TPU
        # gradients are dense scatters where untouched embedding rows
        # are exact zeros, so the native rendering freezes zero rows
        # (params, moments AND decoupled decay) of SPARSE-marKED params
        # only. The eager path reads param.is_sparse_grad; the compiled
        # path needs names (pass parameters= or set_lazy_params()).
        self._lazy = bool(lazy_mode)
        self._lazy_names = {
            getattr(p, "name", None) for p in (parameters or [])
            if getattr(p, "is_sparse_grad", False)} - {None}

    def set_lazy_params(self, names):
        """Names of sparse-embedding params for lazy_mode in the
        functional/compiled path (state_pytree keys)."""
        self._lazy_names = set(names)

    def _lazy_applies(self):
        if not self._lazy:
            return False
        name = self._current_param_name
        if name in self._lazy_names:
            return True
        p = getattr(self, "_current_param_obj", None)
        return bool(getattr(p, "is_sparse_grad", False))

    def _update_rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** stepf)
        vhat = v / (1 - b2 ** stepf)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        self._last_lazy_mask = None
        if self._lazy_applies() and jnp.ndim(g) >= 2:
            touched = jnp.any(g != 0, axis=tuple(range(1, jnp.ndim(g))))
            mask = touched.reshape((-1,) + (1,) * (jnp.ndim(g) - 1))
            new_p = jnp.where(mask, new_p, p)
            m = jnp.where(mask, m, slots["moment1"])
            v = jnp.where(mask, v, slots["moment2"])
            self._last_lazy_mask = mask
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None,
                 accumulator_dtype=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         accumulator_dtype=accumulator_dtype)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_wd(self):
        return True


class Adamax(Optimizer):
    _slot_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_rule(self, p, g, slots, lr, step):
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        stepf = jnp.asarray(step, jnp.float32)
        new_p = p - (lr / (1 - self._beta1 ** stepf)) * m / (u + self._epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    _slot_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _init_slot(self, name, v):
        return jnp.full_like(v, self._initial, dtype=jnp.float32)

    def _update_rule(self, p, g, slots, lr, step):
        mom = slots["moment"] + jnp.square(g)
        new_p = p - lr * g / (jnp.sqrt(mom) + self._epsilon)
        return new_p, {"moment": mom}


class Adadelta(Optimizer):
    _slot_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _update_rule(self, p, g, slots, lr, step):
        eg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt((slots["avg_squared_update"] + self._epsilon)
                           / (eg + self._epsilon)) * g
        eu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * jnp.square(update)
        return p + lr * update, {"avg_squared_grad": eg, "avg_squared_update": eu}


class RMSProp(Optimizer):
    _slot_names = ("mean_square", "mean_grad", "momentum")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _update_rule(self, p, g, slots, lr, step):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = ms - jnp.square(mg) + self._epsilon
        else:
            mg = slots["mean_grad"]
            denom = ms + self._epsilon
        mom = self._momentum * slots["momentum"] + lr * g / jnp.sqrt(denom)
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Lamb(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision)
        self._lamb_wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_rule(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        stepf = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** stepf)
        vhat = v / (1 - b2 ** stepf)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}
