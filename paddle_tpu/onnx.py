"""paddle_tpu.onnx — reference python/paddle/onnx/export.py.
The TPU-native exchange format is StableHLO (jit.save emits it); ONNX export
would need onnx (not in this image)."""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "onnx is not available in this environment; use paddle_tpu.jit.save "
            "which exports StableHLO (portable across XLA runtimes)") from None
