"""paddle_tpu.onnx — ONNX export without the onnx package.

Reference: python/paddle/onnx/export.py (which shells out to paddle2onnx, a
C++ converter from the fluid Program). The TPU-native pipeline has no fluid
Program; instead we trace the layer to a jaxpr (the same IR jit compiles)
and serialize it straight to an ONNX ModelProto, hand-encoding the protobuf
wire format so no third-party onnx dependency is needed.

    export(layer, "model", input_spec=[InputSpec([1, 3, 32, 32])])
    # -> model.onnx  (opset 13, params as initializers)

Covered primitives: elementwise math/logic, matmul (dot_general → MatMul /
Einsum), conv_general_dilated → Conv, reduce_window → Max/AveragePool,
reductions, reshape/transpose/slice/concat/pad/broadcast, select_n → Where,
convert_element_type → Cast, simple gather → Gather (embedding/take), and
inlined pjit/checkpoint/custom-vjp subjaxprs. Unsupported primitives raise
with the primitive names so the gap is explicit, not silent.

A matching minimal wire-format reader lives in `_decode_model` (used by the
tests to round-trip what we emit; also handy for inspecting files).
"""
import struct

import numpy as np

__all__ = ["export"]

# ---------------------------------------------------------------------------
# protobuf wire-format writer (only what ModelProto needs)
# ---------------------------------------------------------------------------


def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def _f_int(field, value):
    return _tag(field, 0) + _varint(value)


def _f_bytes(field, data):
    return _tag(field, 2) + _varint(len(data)) + data


def _f_str(field, s):
    return _f_bytes(field, s.encode("utf-8"))


def _f_float(field, v):
    return _tag(field, 5) + struct.pack("<f", v)


# ONNX TensorProto.DataType
_DTYPES = {
    np.dtype("float32"): 1, np.dtype("uint8"): 2, np.dtype("int8"): 3,
    np.dtype("int16"): 5, np.dtype("int32"): 6, np.dtype("int64"): 7,
    np.dtype("bool"): 9, np.dtype("float16"): 10, np.dtype("float64"): 11,
    np.dtype("uint32"): 12, np.dtype("uint64"): 13,
}
_BFLOAT16 = 16


def _np_dtype_code(arr):
    import jax.numpy as jnp
    if arr.dtype == jnp.bfloat16:
        return _BFLOAT16
    return _DTYPES[np.dtype(arr.dtype)]


def _dtype_code(dtype):
    """TensorProto code for a dtype object (bf16-aware; raises on unknown
    so graph I/O never gets silently mislabeled as FLOAT)."""
    import jax.numpy as jnp
    if dtype == jnp.bfloat16:
        return _BFLOAT16
    try:
        return _DTYPES[np.dtype(dtype)]
    except (KeyError, TypeError):
        raise NotImplementedError(
            f"onnx.export: no TensorProto dtype mapping for {dtype!r}")


def _tensor_proto(name, arr):
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    import jax.numpy as jnp
    code = _np_dtype_code(arr)
    if arr.dtype == jnp.bfloat16:
        raw = np.asarray(arr).view(np.uint16).tobytes()
    else:
        raw = np.ascontiguousarray(np.asarray(arr)).tobytes()
    body = b"".join(_f_int(1, int(d)) for d in arr.shape)
    body += _f_int(2, code) + _f_str(8, name) + _f_bytes(9, raw)
    return body


def _attr(name, value):
    """AttributeProto: name=1 f=2 i=3 s=4 floats=7 ints=8 type=20."""
    body = _f_str(1, name)
    if isinstance(value, float):
        body += _f_float(2, value) + _f_int(20, 1)          # FLOAT
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        body += _f_int(3, int(value)) + _f_int(20, 2)       # INT
    elif isinstance(value, str):
        body += _f_bytes(4, value.encode()) + _f_int(20, 3)  # STRING
    elif isinstance(value, (list, tuple)) and value and isinstance(value[0], float):
        body += b"".join(_f_float(7, v) for v in value) + _f_int(20, 6)
    elif isinstance(value, (list, tuple)):
        body += b"".join(_f_int(8, int(v)) for v in value) + _f_int(20, 7)
    else:
        raise TypeError(f"attr {name}: {type(value)}")
    return body


def _node(op_type, inputs, outputs, name="", **attrs):
    """NodeProto: input=1 output=2 name=3 op_type=4 attribute=5."""
    body = b"".join(_f_str(1, i) for i in inputs)
    body += b"".join(_f_str(2, o) for o in outputs)
    body += _f_str(3, name or outputs[0]) + _f_str(4, op_type)
    body += b"".join(_f_bytes(5, _attr(k, v)) for k, v in attrs.items())
    return body


def _value_info(name, shape, dtype_code):
    # TypeProto.Tensor: elem_type=1 shape=2 ; TensorShapeProto.dim=1 ;
    # Dimension.dim_value=1 ; TypeProto.tensor_type=1 ;
    # ValueInfoProto: name=1 type=2
    dims = b"".join(_f_bytes(1, _f_int(1, int(d))) for d in shape)
    tensor_type = _f_int(1, dtype_code) + _f_bytes(2, dims)
    return _f_str(1, name) + _f_bytes(2, _f_bytes(1, tensor_type))


def _graph(nodes, name, initializers, inputs, outputs):
    """GraphProto: node=1 name=2 initializer=5 input=11 output=12."""
    body = b"".join(_f_bytes(1, n) for n in nodes)
    body += _f_str(2, name)
    body += b"".join(_f_bytes(5, t) for t in initializers)
    body += b"".join(_f_bytes(11, v) for v in inputs)
    body += b"".join(_f_bytes(12, v) for v in outputs)
    return body


def _model(graph, opset=13):
    """ModelProto: ir_version=1 producer_name=2 producer_version=3
    opset_import=8 graph=7 ; OperatorSetId: domain=1 version=2."""
    body = _f_int(1, 8)                       # IR version 8
    body += _f_str(2, "paddle_tpu") + _f_str(3, "1.0")
    body += _f_bytes(7, graph)
    body += _f_bytes(8, _f_str(1, "") + _f_int(2, opset))
    return body


# ---------------------------------------------------------------------------
# minimal reader (round-trip testing / inspection)
# ---------------------------------------------------------------------------


def _read_varint(buf, pos):
    val = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _read_fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, val


def _decode_model(data):
    """Parse a ModelProto (as written by this module) into plain dicts."""
    model = {"opset": None, "graph": None}
    for f, _, v in _read_fields(data):
        if f == 7:
            model["graph"] = _decode_graph(v)
        elif f == 8:
            for f2, _, v2 in _read_fields(v):
                if f2 == 2:
                    model["opset"] = v2
    return model


def _decode_graph(buf):
    g = {"nodes": [], "initializers": {}, "inputs": [], "outputs": []}
    for f, _, v in _read_fields(buf):
        if f == 1:
            node = {"inputs": [], "outputs": [], "op_type": None, "attrs": {}}
            for f2, _, v2 in _read_fields(v):
                if f2 == 1:
                    node["inputs"].append(v2.decode())
                elif f2 == 2:
                    node["outputs"].append(v2.decode())
                elif f2 == 4:
                    node["op_type"] = v2.decode()
                elif f2 == 5:
                    a = dict(name=None, value=None)
                    ints = []
                    for f3, _, v3 in _read_fields(v2):
                        if f3 == 1:
                            a["name"] = v3.decode()
                        elif f3 in (2, 3):
                            a["value"] = v3
                        elif f3 == 4:
                            a["value"] = v3.decode()
                        elif f3 == 8:
                            ints.append(v3)
                    if ints:
                        a["value"] = ints
                    node["attrs"][a["name"]] = a["value"]
            g["nodes"].append(node)
        elif f == 5:
            t = {"dims": [], "name": None, "raw": None, "dtype": None}
            for f2, _, v2 in _read_fields(v):
                if f2 == 1:
                    t["dims"].append(v2)
                elif f2 == 2:
                    t["dtype"] = v2
                elif f2 == 8:
                    t["name"] = v2.decode()
                elif f2 == 9:
                    t["raw"] = v2
            g["initializers"][t["name"]] = t
        elif f in (11, 12):
            name = None
            for f2, _, v2 in _read_fields(v):
                if f2 == 1:
                    name = v2.decode()
            g["inputs" if f == 11 else "outputs"].append(name)
    return g


# ---------------------------------------------------------------------------
# jaxpr -> ONNX graph
# ---------------------------------------------------------------------------

_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "pow": "Pow",
    "max": "Max", "min": "Min", "neg": "Neg", "exp": "Exp", "log": "Log",
    "tanh": "Tanh", "sqrt": "Sqrt", "abs": "Abs", "floor": "Floor",
    "ceil": "Ceil", "round": "Round", "sign": "Sign", "logistic": "Sigmoid",
    "erf": "Erf", "sin": "Sin", "cos": "Cos", "and": "And", "or": "Or",
    "xor": "Xor", "not": "Not", "add_any": "Add",
}
_COMPARE = {"eq": "Equal", "lt": "Less", "le": "LessOrEqual",
            "gt": "Greater", "ge": "GreaterOrEqual"}
_REDUCE_ATTR = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                "reduce_prod": "ReduceProd"}


class _Exporter:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.names = {}          # jax Var -> onnx name
        self.counter = 0
        self.unsupported = set()

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def name_of(self, atom):
        from jax.extend import core as jcore
        if isinstance(atom, jcore.Literal):
            return self.const(np.asarray(atom.val), "lit")
        return self.names[atom]

    def emit(self, op, ins, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op, ins, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    # -- primitive handlers ------------------------------------------------

    def run_jaxpr(self, jaxpr, consts, in_names):
        for var, const in zip(jaxpr.constvars, consts):
            self.names[var] = self.const(const, "param")
        for var, name in zip(jaxpr.invars, in_names):
            self.names[var] = name
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.name_of(v) for v in jaxpr.outvars]

    def eqn(self, eqn):
        p = eqn.primitive.name
        handler = getattr(self, f"p_{p}", None)
        if handler is not None:
            return handler(eqn)
        if p in _ELEMENTWISE:
            ins = [self.name_of(v) for v in eqn.invars]
            self.names[eqn.outvars[0]] = self.emit(_ELEMENTWISE[p], ins)
            return
        if p in _COMPARE:
            ins = [self.name_of(v) for v in eqn.invars]
            self.names[eqn.outvars[0]] = self.emit(_COMPARE[p], ins)
            return
        if p == "ne":
            ins = [self.name_of(v) for v in eqn.invars]
            self.names[eqn.outvars[0]] = self.emit(
                "Not", [self.emit("Equal", ins)])
            return
        if p in _REDUCE_ATTR:
            self.names[eqn.outvars[0]] = self.emit(
                _REDUCE_ATTR[p], [self.name_of(eqn.invars[0])],
                axes=list(eqn.params["axes"]), keepdims=0)
            return
        if p in ("jit", "pjit", "closed_call", "core_call", "remat2",
                 "checkpoint"):
            sub = eqn.params.get("jaxpr")
            closed = sub if hasattr(sub, "jaxpr") else None
            inner = closed.jaxpr if closed else sub
            consts = closed.consts if closed else []
            outs = self.run_jaxpr(inner, consts,
                                  [self.name_of(v) for v in eqn.invars])
            for var, name in zip(eqn.outvars, outs):
                self.names[var] = name
            return
        if p in ("custom_jvp_call", "custom_vjp_call",
                 "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
            # export the PRIMAL graph: ONNX carries no autodiff rules,
            # so the custom forward/backward pair reduces to its
            # fun_jaxpr (the *_call_jaxpr spelling is what this jaxlib
            # stages nn ops like layer_norm through; its invars line up
            # 1:1 with the eqn's — num_consts leading)
            closed = (eqn.params.get("call_jaxpr")
                      or eqn.params.get("fun_jaxpr"))
            outs = self.run_jaxpr(closed.jaxpr, closed.consts,
                                  [self.name_of(v) for v in eqn.invars])
            for var, name in zip(eqn.outvars, outs):
                self.names[var] = name
            return
        if p in ("stop_gradient", "copy", "sharding_constraint"):
            self.names[eqn.outvars[0]] = self.name_of(eqn.invars[0])
            return
        self.unsupported.add(p)
        # placeholder so later eqns can still name their inputs
        for var in eqn.outvars:
            self.names[var] = self.fresh(f"unsupported_{p}")

    def p_convert_element_type(self, eqn):
        import jax.numpy as jnp
        new = eqn.params["new_dtype"]
        if new == jnp.bfloat16:
            code = _BFLOAT16
        else:
            try:
                code = _DTYPES[np.dtype(new)]
            except (KeyError, TypeError):
                self.unsupported.add(f"convert_element_type({new})")
                self.names[eqn.outvars[0]] = self.fresh("unsupported_cast")
                return
        self.names[eqn.outvars[0]] = self.emit(
            "Cast", [self.name_of(eqn.invars[0])], to=code)

    def p_integer_pow(self, eqn):
        x = self.name_of(eqn.invars[0])
        y = eqn.params["y"]
        self.names[eqn.outvars[0]] = self.emit(
            "Pow", [x, self.const(np.float32(y))])

    def p_erfc(self, eqn):
        x = self.name_of(eqn.invars[0])
        one = self.const(np.float32(1.0))
        self.names[eqn.outvars[0]] = self.emit(
            "Sub", [one, self.emit("Erf", [x])])

    def p_square(self, eqn):
        x = self.name_of(eqn.invars[0])
        self.names[eqn.outvars[0]] = self.emit("Mul", [x, x])

    def p_rsqrt(self, eqn):
        x = self.name_of(eqn.invars[0])
        self.names[eqn.outvars[0]] = self.emit(
            "Reciprocal", [self.emit("Sqrt", [x])])

    def p_reshape(self, eqn):
        if eqn.params.get("dimensions") is not None:
            # transposing reshape: ONNX Reshape is row-major only
            self.unsupported.add("reshape(dimensions)")
            self.names[eqn.outvars[0]] = self.fresh("unsupported_reshape")
            return
        shape = self.const(np.asarray(eqn.params["new_sizes"], np.int64))
        self.names[eqn.outvars[0]] = self.emit(
            "Reshape", [self.name_of(eqn.invars[0]), shape])

    def p_squeeze(self, eqn):
        shape = self.const(np.asarray(eqn.outvars[0].aval.shape, np.int64))
        self.names[eqn.outvars[0]] = self.emit(
            "Reshape", [self.name_of(eqn.invars[0]), shape])

    def p_transpose(self, eqn):
        self.names[eqn.outvars[0]] = self.emit(
            "Transpose", [self.name_of(eqn.invars[0])],
            perm=list(eqn.params["permutation"]))

    def p_broadcast_in_dim(self, eqn):
        x = self.name_of(eqn.invars[0])
        out_shape = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        # Reshape to rank(out) with 1s, then Expand
        interim = [1] * len(out_shape)
        for src, dst in enumerate(bdims):
            interim[dst] = eqn.invars[0].aval.shape[src]
        r = self.emit("Reshape",
                      [x, self.const(np.asarray(interim, np.int64))])
        self.names[eqn.outvars[0]] = self.emit(
            "Expand", [r, self.const(np.asarray(out_shape, np.int64))])

    def p_select_n(self, eqn):
        if len(eqn.invars) != 3:
            self.unsupported.add("select_n(>2 cases)")
            self.names[eqn.outvars[0]] = self.fresh("unsupported_select")
            return
        pred, case0, case1 = [self.name_of(v) for v in eqn.invars]
        self.names[eqn.outvars[0]] = self.emit("Where", [pred, case1, case0])

    def p_concatenate(self, eqn):
        ins = [self.name_of(v) for v in eqn.invars]
        self.names[eqn.outvars[0]] = self.emit(
            "Concat", ins, axis=eqn.params["dimension"])

    def p_slice(self, eqn):
        pr = eqn.params
        starts = np.asarray(pr["start_indices"], np.int64)
        ends = np.asarray(pr["limit_indices"], np.int64)
        axes = np.arange(len(starts), dtype=np.int64)
        steps = np.asarray(pr["strides"] or [1] * len(starts), np.int64)
        self.names[eqn.outvars[0]] = self.emit(
            "Slice", [self.name_of(eqn.invars[0]), self.const(starts),
                      self.const(ends), self.const(axes), self.const(steps)])

    def p_pad(self, eqn):
        cfg = eqn.params["padding_config"]
        if any(interior for _, _, interior in cfg):
            self.unsupported.add("pad(interior)")
        lo = [l for l, _, _ in cfg]
        hi = [h for _, h, _ in cfg]
        pads = self.const(np.asarray(lo + hi, np.int64))
        x, val = self.name_of(eqn.invars[0]), self.name_of(eqn.invars[1])
        self.names[eqn.outvars[0]] = self.emit("Pad", [x, pads, val],
                                               mode="constant")

    def p_rev(self, eqn):
        # ONNX has no Reverse; Slice with negative steps
        x = self.name_of(eqn.invars[0])
        dims = list(eqn.params["dimensions"])
        starts = self.const(np.asarray([-1] * len(dims), np.int64))
        ends = self.const(np.asarray([np.iinfo(np.int64).min + 1] * len(dims),
                                     np.int64))
        axes = self.const(np.asarray(dims, np.int64))
        steps = self.const(np.asarray([-1] * len(dims), np.int64))
        self.names[eqn.outvars[0]] = self.emit(
            "Slice", [x, starts, ends, axes, steps])

    def p_iota(self, eqn):
        pr = eqn.params
        arr = np.reshape(
            np.broadcast_to(
                np.expand_dims(
                    np.arange(pr["shape"][pr["dimension"]],
                              dtype=np.dtype(pr["dtype"])),
                    [d for d in range(len(pr["shape"]))
                     if d != pr["dimension"]]),
                pr["shape"]), pr["shape"])
        self.names[eqn.outvars[0]] = self.const(arr, "iota")

    def p_argmax(self, eqn):
        self._arg_reduce(eqn, "ArgMax")

    def p_argmin(self, eqn):
        self._arg_reduce(eqn, "ArgMin")

    def _arg_reduce(self, eqn, op):
        axes = eqn.params["axes"]
        out = self.emit(op, [self.name_of(eqn.invars[0])],
                        axis=int(axes[0]), keepdims=0)
        code = _DTYPES.get(np.dtype(eqn.params["index_dtype"]), 7)
        if code != 7:   # ONNX Arg* returns int64
            out = self.emit("Cast", [out], to=code)
        self.names[eqn.outvars[0]] = out

    def p_reduce_sum(self, eqn):
        axes = self.const(np.asarray(eqn.params["axes"], np.int64))
        self.names[eqn.outvars[0]] = self.emit(
            "ReduceSum", [self.name_of(eqn.invars[0]), axes], keepdims=0)

    def p_dot_general(self, eqn):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars
        l_name, r_name = self.name_of(lhs), self.name_of(rhs)
        lr, rr = len(lhs.aval.shape), len(rhs.aval.shape)
        # clean matmul: contract last of lhs with second-to-last (or only
        # other) dim of rhs, batch dims leading and aligned
        std_batch = (tuple(lb) == tuple(range(len(lb)))
                     and tuple(rb) == tuple(range(len(rb))))
        # MatMul only for the exact [batch..., m, k] @ [batch..., k, n]
        # shape (one free dim each side); anything else -> Einsum, whose
        # output-axis order matches dot_general's (batch, lhs-free,
        # rhs-free) — MatMul's does not for >1 free dim
        if (len(lc) == 1 and len(rc) == 1 and std_batch
                and lr == len(lb) + 2 and rr == len(rb) + 2
                and lc[0] == lr - 1 and rc[0] == rr - 2):
            self.names[eqn.outvars[0]] = self.emit("MatMul", [l_name, r_name])
            return
        # general: Einsum
        letters = "abcdefghijklmnopqrstuvwxyz"
        it = iter(letters)
        l_ax = [None] * lr
        r_ax = [None] * rr
        for i, j in zip(lb, rb):
            c = next(it)
            l_ax[i] = r_ax[j] = c
        for i, j in zip(lc, rc):
            c = next(it)
            l_ax[i] = r_ax[j] = c
        for ax in (l_ax, r_ax):
            for i in range(len(ax)):
                if ax[i] is None:
                    ax[i] = next(it)
        out_ax = ([l_ax[i] for i in lb]
                  + [l_ax[i] for i in range(lr) if i not in lb + lc]
                  + [r_ax[i] for i in range(rr) if i not in rb + rc])
        eq = f"{''.join(l_ax)},{''.join(r_ax)}->{''.join(out_ax)}"
        self.names[eqn.outvars[0]] = self.emit(
            "Einsum", [l_name, r_name], equation=eq)

    def p_conv_general_dilated(self, eqn):
        pr = eqn.params
        dn = pr["dimension_numbers"]
        lhs_spec, rhs_spec, out_spec = dn
        x = self.name_of(eqn.invars[0])
        w = self.name_of(eqn.invars[1])
        nd = len(lhs_spec) - 2
        if pr["lhs_dilation"] != (1,) * nd:
            self.unsupported.add("conv(lhs_dilation)")
            self.names[eqn.outvars[0]] = self.fresh("unsupported_conv")
            return
        # lhs_spec is (batch_dim, feature_dim, *spatial_dims) as dim INDICES
        # of the operand; transposing by it puts the input in NCHW. Same
        # for the kernel spec (out_feature, in_feature, *spatial) -> OIHW.
        perm_in = [lhs_spec[0], lhs_spec[1]] + list(lhs_spec[2:])
        if perm_in != list(range(len(perm_in))):
            x = self.emit("Transpose", [x], perm=perm_in)
        perm_w = [rhs_spec[0], rhs_spec[1]] + list(rhs_spec[2:])
        if perm_w != list(range(len(perm_w))):
            w = self.emit("Transpose", [w], perm=perm_w)
        pads_lo = [p[0] for p in pr["padding"]]
        pads_hi = [p[1] for p in pr["padding"]]
        kshape = [eqn.invars[1].aval.shape[d] for d in rhs_spec[2:]]
        conv = self.emit("Conv", [x, w],
                         kernel_shape=kshape,
                         strides=list(pr["window_strides"]),
                         pads=pads_lo + pads_hi,
                         dilations=list(pr["rhs_dilation"]),
                         group=pr["feature_group_count"])
        # back to the jaxpr's output layout
        out_perm = list(np.argsort([out_spec[0], out_spec[1]]
                                   + list(out_spec[2:])))
        if out_perm != list(range(len(out_perm))):
            conv = self.emit("Transpose", [conv], perm=out_perm)
        self.names[eqn.outvars[0]] = conv

    def p_reduce_window_max(self, eqn):
        self._pool(eqn, "MaxPool")

    def p_reduce_window_sum(self, eqn):
        # AveragePool(count_include_pad=1) * window_size == window sum
        # exactly, including padded border windows.
        pr = eqn.params
        n = int(np.prod(pr["window_dimensions"]))
        pooled = self._pool(eqn, "AveragePool", assign=False,
                            count_include_pad=1)
        scaled = self.emit("Mul", [pooled, self.const(np.float32(n))])
        self.names[eqn.outvars[0]] = scaled

    def _pool(self, eqn, op, assign=True, **extra):
        pr = eqn.params
        wd = pr["window_dimensions"]
        ws = pr["window_strides"]
        pad = pr["padding"]
        rank = len(wd)
        # a dim takes part in the pooling if its window, stride or padding
        # is non-trivial (kernel (2,1) + stride 2 pools W with window 1)
        spatial = [i for i in range(rank)
                   if wd[i] != 1 or ws[i] != 1 or pad[i] != (0, 0)] or \
            list(range(2, rank))
        x = self.name_of(eqn.invars[0])
        # ONNX pools the trailing dims of an NC<spatial> tensor; transpose
        # other layouts (e.g. NHWC channels_last: window (1,kh,kw,1)) in
        # and back out
        non_spatial = [i for i in range(rank) if i not in spatial]
        if len(non_spatial) != 2:   # ONNX pools N,C + spatial exactly
            self.unsupported.add(f"{eqn.primitive.name}(layout)")
            out = self.fresh("unsupported_pool")
            if assign:
                self.names[eqn.outvars[0]] = out
            return out
        nchw_spatial = list(range(rank - len(spatial), rank))
        perm = None
        if spatial != nchw_spatial:
            perm = non_spatial + spatial
            x = self.emit("Transpose", [x], perm=perm)
        kernel = [wd[i] for i in spatial]
        strides = [ws[i] for i in spatial]
        pads = [pad[i][0] for i in spatial] + [pad[i][1] for i in spatial]
        out = self.emit(op, [x], kernel_shape=kernel, strides=strides,
                        pads=pads, **extra)
        if perm is not None:
            out = self.emit("Transpose", [out],
                            perm=list(np.argsort(perm)))
        if assign:
            self.names[eqn.outvars[0]] = out
        return out

    def p_gather(self, eqn):
        """Narrow translation: the jnp.take/embedding pattern (single
        collapsed axis, full slices elsewhere) -> ONNX Gather."""
        pr = eqn.params
        dn = pr["dimension_numbers"]
        operand, indices = eqn.invars
        op_shape = operand.aval.shape
        slice_sizes = pr["slice_sizes"]
        collapsed = dn.collapsed_slice_dims
        start_map = dn.start_index_map
        if (len(collapsed) == 1 and len(start_map) == 1
                and collapsed == start_map
                and slice_sizes[collapsed[0]] == 1
                and all(slice_sizes[i] == op_shape[i]
                        for i in range(len(op_shape)) if i != collapsed[0])):
            axis = collapsed[0]
            idx = self.name_of(indices)
            # gather indices carry a trailing unit "index vector" dim
            if indices.aval.shape and indices.aval.shape[-1] == 1:
                idx = self.emit("Reshape", [idx, self.const(
                    np.asarray(indices.aval.shape[:-1], np.int64))])
            self.names[eqn.outvars[0]] = self.emit(
                "Gather", [self.name_of(operand), idx], axis=axis)
            return
        self.unsupported.add("gather(general)")
        self.names[eqn.outvars[0]] = self.fresh("unsupported_gather")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace `layer` and write `path + '.onnx'`.

    input_spec: list of static.InputSpec (or arrays) describing the inputs;
    required unless the layer was already called (then its last input
    shapes would be needed — pass the spec explicitly for determinism).
    """
    import jax
    import numpy as np

    from .framework.core import Tensor
    from .nn.layer_base import buffer_pytree, functional_call, state_pytree

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    if opset_version != 13:
        raise ValueError(
            f"onnx.export emits opset-13-form ops (ReduceSum axes-as-input "
            f"etc.); opset_version={opset_version} would mislabel the file")

    def example(spec):
        shape = [1 if (d is None or d < 0) else int(d)
                 for d in getattr(spec, "shape", spec)]
        dtype = str(getattr(spec, "dtype", "float32")).replace("paddle.", "")
        return np.zeros(shape, dtype)

    examples = [example(s) for s in input_spec]
    params = state_pytree(layer)
    params.update(buffer_pytree(layer))
    was_training = getattr(layer, "training", False)
    layer.eval()

    def pure(*xs):
        with functional_call(layer, params):
            out = layer(*[Tensor(x) for x in xs])
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    try:
        closed = jax.make_jaxpr(pure)(*examples)
    finally:
        if was_training:
            layer.train()
    ex = _Exporter()
    in_names = [f"input_{i}" for i in range(len(examples))]
    out_names = ex.run_jaxpr(closed.jaxpr, closed.consts, in_names)
    if ex.unsupported:
        raise NotImplementedError(
            "onnx.export: unsupported primitives in traced graph: "
            + ", ".join(sorted(ex.unsupported)))

    inputs = [_value_info(n, e.shape, _dtype_code(e.dtype))
              for n, e in zip(in_names, examples)]
    outputs = []
    outvals = closed.out_avals
    for n, av in zip(out_names, outvals):
        outputs.append(_value_info(n, av.shape, _dtype_code(av.dtype)))
    graph = _graph(ex.nodes, "paddle_tpu_graph", ex.initializers,
                   inputs, outputs)
    data = _model(graph, opset=opset_version)
    out_path = str(path)
    if not out_path.endswith(".onnx"):
        out_path += ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path
