"""py2/3 text helpers — reference python/paddle/compat.py."""

__all__ = ["to_text", "to_bytes", "floor_division", "get_exception_message"]


def _convert(obj, conv, inplace):
    if obj is None or isinstance(obj, (int, float)):
        return obj
    if isinstance(obj, (bytes, str)):
        return conv(obj)
    if isinstance(obj, list):
        if inplace:
            for i, v in enumerate(obj):
                obj[i] = _convert(v, conv, inplace)
            return obj
        return [_convert(v, conv, inplace) for v in obj]
    if isinstance(obj, set):
        out = {_convert(v, conv, False) for v in obj}
        if inplace:
            obj.clear()
            obj.update(out)
            return obj
        return out
    if isinstance(obj, dict):
        out = {_convert(k, conv, False): _convert(v, conv, False)
               for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(out)
            return obj
        return out
    return obj


def to_text(obj, encoding="utf-8", inplace=False):
    return _convert(obj, lambda s: s.decode(encoding) if isinstance(s, bytes) else s, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    return _convert(obj, lambda s: s.encode(encoding) if isinstance(s, str) else s, inplace)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
