"""Bijective transforms for distributions.

API parity with reference python/paddle/distribution/transform.py (class
names, forward/inverse/log-det-jacobian/shape methods). Implementation is
jnp-native so transforms compose under jax.jit and autodiff.
"""
import enum
import functools
import operator

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class Transform:
    _type = Type.INJECTION

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, input):
        from . import Distribution, TransformedDistribution
        if isinstance(input, Distribution):
            return TransformedDistribution(input, [self])
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        return self.forward(input)

    def forward(self, x):
        return Tensor(self._forward(_val(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_val(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._call_forward_log_det_jacobian(_val(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(self._call_inverse_log_det_jacobian(_val(y)))

    def forward_shape(self, shape):
        return self._forward_shape(tuple(shape))

    def inverse_shape(self, shape):
        return self._inverse_shape(tuple(shape))

    # -- overridable raw-array hooks -------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _call_forward_log_det_jacobian(self, x):
        if hasattr(self, "_forward_log_det_jacobian"):
            return self._forward_log_det_jacobian(x)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return -self._inverse_log_det_jacobian(self._forward(x))
        raise NotImplementedError

    def _call_inverse_log_det_jacobian(self, y):
        if hasattr(self, "_inverse_log_det_jacobian"):
            return self._inverse_log_det_jacobian(y)
        if hasattr(self, "_forward_log_det_jacobian"):
            return -self._forward_log_det_jacobian(self._inverse(y))
        raise NotImplementedError

    def _forward_shape(self, shape):
        return shape

    def _inverse_shape(self, shape):
        return shape


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return -y, y

    def _inverse_log_det_jacobian(self, y):
        zero = jnp.zeros_like(y)
        return zero, zero

    def inverse(self, y):
        lo, hi = self._inverse(_val(y))
        return Tensor(lo), Tensor(hi)

    def inverse_log_det_jacobian(self, y):
        lo, hi = self._inverse_log_det_jacobian(_val(y))
        return Tensor(lo), Tensor(hi)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self._loc = _val(loc)
        self._scale = _val(scale)

    @property
    def loc(self):
        return Tensor(self._loc)

    @property
    def scale(self):
        return Tensor(self._scale)

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self._scale)), x.shape)

    def _forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(
            shape, self._loc.shape, self._scale.shape))

    _inverse_shape = _forward_shape


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    @classmethod
    def _is_injective(cls):
        return True

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _call_forward_log_det_jacobian(self, x):
        value = 0.0
        event_rank = 0
        for t in self.transforms:
            value += _sum_rightmost(
                t._call_forward_log_det_jacobian(x), event_rank)
            x = t._forward(x)
        return value

    def _forward_shape(self, shape):
        for t in self.transforms:
            shape = t._forward_shape(shape)
        return shape

    def _inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t._inverse_shape(shape)
        return shape


def _sum_rightmost(value, n):
    return value.sum(axis=tuple(range(-n, 0))) if n > 0 else value


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Transform):
            raise TypeError("base must be a Transform")
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank must be positive")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self._base._forward(x)

    def _inverse(self, y):
        return self._base._inverse(y)

    def _call_forward_log_det_jacobian(self, x):
        return _sum_rightmost(
            self._base._call_forward_log_det_jacobian(x),
            self._reinterpreted_batch_rank)

    def _forward_shape(self, shape):
        return self._base._forward_shape(shape)

    def _inverse_shape(self, shape):
        return self._base._inverse_shape(shape)


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self._power = _val(power)

    @property
    def power(self):
        return Tensor(self._power)

    def _forward(self, x):
        return jnp.power(x, self._power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self._power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self._power * jnp.power(x, self._power - 1)))

    def _forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(shape, self._power.shape))

    _inverse_shape = _forward_shape


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        in_event_shape = tuple(in_event_shape)
        out_event_shape = tuple(out_event_shape)
        if (functools.reduce(operator.mul, in_event_shape, 1)
                != functools.reduce(operator.mul, out_event_shape, 1)):
            raise ValueError("in/out event sizes must match")
        self._in_event_shape = in_event_shape
        self._out_event_shape = out_event_shape

    @property
    def in_event_shape(self):
        return self._in_event_shape

    @property
    def out_event_shape(self):
        return self._out_event_shape

    def _forward(self, x):
        n = len(self._in_event_shape)
        batch = x.shape[:x.ndim - n] if n else x.shape
        return x.reshape(batch + self._out_event_shape)

    def _inverse(self, y):
        n = len(self._out_event_shape)
        batch = y.shape[:y.ndim - n] if n else y.shape
        return y.reshape(batch + self._in_event_shape)

    def _forward_log_det_jacobian(self, x):
        n = len(self._in_event_shape)
        batch = x.shape[:x.ndim - n] if n else x.shape
        return jnp.zeros(batch, x.dtype)

    def _forward_shape(self, shape):
        n = len(self._in_event_shape)
        if tuple(shape[len(shape) - n:]) != self._in_event_shape:
            raise ValueError("shape mismatch for ReshapeTransform")
        return tuple(shape[:len(shape) - n]) + self._out_event_shape

    def _inverse_shape(self, shape):
        n = len(self._out_event_shape)
        if tuple(shape[len(shape) - n:]) != self._out_event_shape:
            raise ValueError("shape mismatch for ReshapeTransform")
        return tuple(shape[:len(shape) - n]) + self._in_event_shape


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transform):
    _type = Type.OTHER

    def _forward(self, x):
        x = jnp.exp(x - jnp.max(x, axis=-1, keepdims=True))
        return x / x.sum(axis=-1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_shape(self, shape):
        if len(shape) < 1:
            raise ValueError("input must have rank >= 1")
        return shape

    _inverse_shape = _forward_shape


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self._transforms = list(transforms)
        self._axis = int(axis)

    @property
    def transforms(self):
        return self._transforms

    @property
    def axis(self):
        return self._axis

    def _split(self, x):
        return [jnp.squeeze(s, self._axis)
                for s in jnp.split(x, len(self._transforms), axis=self._axis)]

    def _forward(self, x):
        return jnp.stack(
            [t._forward(s) for t, s in zip(self._transforms, self._split(x))],
            axis=self._axis)

    def _inverse(self, y):
        return jnp.stack(
            [t._inverse(s) for t, s in zip(self._transforms, self._split(y))],
            axis=self._axis)

    def _call_forward_log_det_jacobian(self, x):
        return jnp.stack(
            [t._call_forward_log_det_jacobian(s)
             for t, s in zip(self._transforms, self._split(x))],
            axis=self._axis)


class StickBreakingTransform(Transform):
    """R^{K-1} -> simplex^K via the stick-breaking construction."""
    _type = Type.BIJECTION

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        z_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad = [(0, 0)] * (x.ndim - 1) + [(0, 1)]
        return (jnp.pad(z, pad, constant_values=1.0)
                * jnp.pad(z_cumprod, [(0, 0)] * (x.ndim - 1) + [(1, 0)],
                          constant_values=1.0))

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = jnp.arange(y_crop.shape[-1], 0, -1, dtype=y.dtype)
        sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
        sf = jnp.concatenate(
            [jnp.ones_like(y_crop[..., :1]), sf[..., :-1]], axis=-1)
        z = y_crop / sf
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        y = self._forward(x)
        offset = jnp.arange(x.shape[-1], 0, -1, dtype=x.dtype)
        xs = x - jnp.log(offset)
        return jnp.sum(-xs + jax.nn.log_sigmoid(xs) + jnp.log(y[..., :-1]),
                       axis=-1)

    def _forward_shape(self, shape):
        if not shape:
            raise ValueError("input must have rank >= 1")
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def _inverse_shape(self, shape):
        if not shape:
            raise ValueError("input must have rank >= 1")
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log|d tanh(x)/dx| = log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))
