"""Probability distributions — reference python/paddle/distribution/*."""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ..framework.random import next_key

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Beta",
           "Dirichlet", "Multinomial", "ExponentialFamily", "Independent",
           "TransformedDistribution", "kl_divergence", "register_kl",
           "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "IndependentTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), self.batch_shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        z = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _val(value)
        var = jnp.square(self.scale)
        return Tensor(-jnp.square(v - self.loc) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale), self.batch_shape))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    """Reference distribution/categorical.py — NOTE its two-faced
    normalization, reproduced faithfully: `logits` are treated as
    nonnegative relative WEIGHTS for probs/log_prob/sample
    (categorical.py:118 divides by the sum), but entropy and
    kl_divergence exponentiate them as real logits
    (categorical.py:218-262 softmax)."""

    def __init__(self, logits, name=None):
        lv = _val(logits)
        self.raw = lv
        self.logits = lv - jax.scipy.special.logsumexp(lv, axis=-1, keepdims=True)
        # sum-normalized weights (reference _prob) + their log, computed
        # once: log_prob/sample in a training loop must not re-reduce
        self._probs = lv / jnp.sum(lv, axis=-1, keepdims=True)
        self._log_probs = jnp.log(self._probs)
        super().__init__(lv.shape[:-1])

    @property
    def probs_array(self):
        return self._probs

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.categorical(
            next_key(), self._log_probs, shape=shape))

    def _gather(self, table, value):
        """Per-element category lookup with value/batch broadcasting —
        the reference's own docstring queries a BATCH of categories
        against an unbatched distribution (categorical.py log_prob
        example: Categorical(x[6]).log_prob([2,1,3]) -> [3])."""
        idx = _val(value).astype(jnp.int32)
        b = jnp.broadcast_shapes(table.shape[:-1], idx.shape)
        table_b = jnp.broadcast_to(table, b + table.shape[-1:])
        idx_b = jnp.broadcast_to(idx, b)
        return Tensor(jnp.take_along_axis(table_b, idx_b[..., None],
                                          axis=-1)[..., 0])

    def log_prob(self, value):
        return self._gather(self._log_probs, value)

    def probs(self, value):
        return self._gather(self.probs_array, value)

    def entropy(self):
        # softmax convention (reference categorical.py:258-262)
        p = jnp.exp(self.logits)
        return Tensor(-jnp.sum(p * self.logits, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (jnp.square(t) * (t + 1)))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        v = _val(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha) + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        dg = jax.scipy.special.digamma
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(next_key(), self.concentration, shape))

    def log_prob(self, value):
        v = _val(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) \
            - jax.scipy.special.gammaln(jnp.sum(a, -1))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(jax.scipy.special.gammaln(a), -1) - jax.scipy.special.gammaln(a0)
        dg = jax.scipy.special.digamma
        return Tensor(lnB + (a0 - k) * dg(a0) - jnp.sum((a - 1) * dg(a), -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.p = _val(probs)
        self.p = self.p / jnp.sum(self.p, -1, keepdims=True)
        super().__init__(self.p.shape[:-1], self.p.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        logits = jnp.log(jnp.maximum(self.p, 1e-30))
        draws = jax.random.categorical(next_key(), logits,
                                       shape=(self.total_count,) + shape)
        k = self.p.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return Tensor(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = _val(value)
        logf = jax.scipy.special.gammaln(jnp.asarray(self.total_count + 1.0)) \
            - jnp.sum(jax.scipy.special.gammaln(v + 1.0), -1)
        return Tensor(logf + jnp.sum(v * jnp.log(jnp.maximum(self.p, 1e-30)), -1))


class ExponentialFamily(Distribution):
    pass


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = reinterpreted_batch_rank
        super().__init__(base.batch_shape[:-reinterpreted_batch_rank],
                         base.batch_shape[-reinterpreted_batch_rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return apply_op(lambda v: jnp.sum(v, axis=tuple(range(-self.rank, 0))), lp)

    def entropy(self):
        e = self.base.entropy()
        return apply_op(lambda v: jnp.sum(v, axis=tuple(range(-self.rank, 0))), e)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else [transforms]
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = None
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ladj = t.forward_log_det_jacobian(x)
            lp = ladj if lp is None else lp + ladj
            y = x
        base_lp = self.base.log_prob(y)
        return base_lp - lp


_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    raise NotImplementedError(f"no KL({type(p).__name__} || {type(q).__name__}) registered")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    # softmax convention on both sides (reference categorical.py:218-223)
    pr = jnp.exp(p.logits)
    return Tensor(jnp.sum(pr * (p.logits - q.logits), axis=-1))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln

    def lbeta(a, b):
        return gl(a) + gl(b) - gl(a + b)
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t = (lbeta(a2, b2) - lbeta(a1, b1)
         + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
         + (a2 - a1 + b2 - b1) * dg(a1 + b1))
    return Tensor(t)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    dg = jax.scipy.special.digamma
    gl = jax.scipy.special.gammaln
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1)
    t = (gl(a0) - jnp.sum(gl(a), -1) - gl(jnp.sum(b, -1)) + jnp.sum(gl(b), -1)
         + jnp.sum((a - b) * (dg(a) - dg(a0)[..., None]), -1))
    return Tensor(t)


from .transform import (  # noqa: E402
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform)
from . import transform  # noqa: E402
