"""FFT — reference python/paddle/fft.py, on jnp.fft (XLA FFT on device)."""
import jax.numpy as jnp

from .framework.core import apply_op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _make1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: fn(v, n=n, axis=axis, norm=norm), x)
    op.__name__ = name
    return op


def _make2(name, fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda v: fn(v, s=s, axes=axes, norm=norm), x)
    op.__name__ = name
    return op


fft = _make1("fft", jnp.fft.fft)
ifft = _make1("ifft", jnp.fft.ifft)
rfft = _make1("rfft", jnp.fft.rfft)
irfft = _make1("irfft", jnp.fft.irfft)
hfft = _make1("hfft", jnp.fft.hfft)
ihfft = _make1("ihfft", jnp.fft.ihfft)
fft2 = _make2("fft2", jnp.fft.fft2)
ifft2 = _make2("ifft2", jnp.fft.ifft2)
rfft2 = _make2("rfft2", jnp.fft.rfft2)
irfft2 = _make2("irfft2", jnp.fft.irfft2)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: jnp.fft.fftn(v, s=s, axes=axes, norm=norm), x)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: jnp.fft.ifftn(v, s=s, axes=axes, norm=norm), x)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: jnp.fft.rfftn(v, s=s, axes=axes, norm=norm), x)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: jnp.fft.irfftn(v, s=s, axes=axes, norm=norm), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D FFT of a Hermitian-symmetric signal — reference python/paddle/fft.py:hfft2."""
    return apply_op(lambda v: _hermitian_fftn(v, s, axes, norm, inverse=False), x)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(lambda v: _hermitian_fftn(v, s, axes, norm, inverse=True), x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: _hermitian_fftn(v, s, axes, norm, inverse=False), x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(lambda v: _hermitian_fftn(v, s, axes, norm, inverse=True), x)


def _hermitian_fftn(v, s, axes, norm, inverse):
    """hfftn = conj-irfftn analog: full FFT over leading axes, Hermitian
    transform on the last axis (numpy hfft/ihfft composition)."""
    if axes is None:
        axes = tuple(range(v.ndim))
    axes = tuple(a % v.ndim for a in axes)
    last = axes[-1]
    lead = axes[:-1]
    if inverse:
        out = jnp.fft.ihfft(v, n=None if s is None else s[-1], axis=last, norm=norm)
        if lead:
            out = jnp.fft.ifftn(out, s=None if s is None else s[:-1], axes=lead, norm=norm)
        return out
    out = v
    if lead:
        out = jnp.fft.fftn(out, s=None if s is None else s[:-1], axes=lead, norm=norm)
    return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=last, norm=norm)


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
