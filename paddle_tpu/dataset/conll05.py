"""reference python/paddle/dataset/conll05.py reader API — delegates to
the real SRL parser in paddle_tpu.text.Conll05st."""
from ..text import Conll05st as _Conll05st

__all__ = ["get_dict", "get_embedding", "test"]


_CACHE = {}


def _ds(**kw):
    key = tuple(sorted(kw.items()))
    if key not in _CACHE:
        _CACHE[key] = _Conll05st(**kw)
    return _CACHE[key]


def get_dict(**kw):
    return _ds(**kw).get_dict()


def get_embedding(**kw):
    return _ds(**kw).get_embedding()


def test(**kw):
    def read():
        ds = _ds(**kw)
        for i in range(len(ds)):
            yield ds[i]
    return read
