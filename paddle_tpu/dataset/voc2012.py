"""reference python/paddle/dataset/voc2012.py reader API — delegates to
vision.datasets.VOC2012 for a real VOCtrainval archive; synthetic
fallback otherwise. Reference split mapping: train()->'trainval',
test()->'train', val()->'val' (dataset/voc2012.py:78-90)."""
import numpy as np

__all__ = ["train", "test", "val"]

_SPLIT = {"train": "trainval", "test": "train", "val": "valid"}


def _reader(api_mode, n, data_file):
    def read():
        if data_file:
            from ..vision.datasets import VOC2012
            ds = VOC2012(data_file=data_file, mode=_SPLIT[api_mode])
            for i in range(len(ds)):
                img, label = ds[i]
                yield np.asarray(img), np.asarray(label)
            return
        rng = np.random.RandomState(
            {"train": 0, "test": 1, "val": 2}[api_mode])
        for _ in range(n):
            yield rng.rand(3, 32, 32).astype("float32"), \
                rng.randint(0, 21, (32, 32)).astype("int64")
    return read


def train(data_file=None, n=64):
    return _reader("train", n, data_file)


def test(data_file=None, n=16):
    return _reader("test", n, data_file)


def val(data_file=None, n=16):
    return _reader("val", n, data_file)
