"""reference python/paddle/dataset/cifar.py reader API (synthetic)."""
import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(n, classes, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img = rng.rand(3072).astype("float32")
            yield img, int(rng.randint(0, classes))
    return read


def train10(n=1024):
    return _reader(n, 10, 0)


def test10(n=256):
    return _reader(n, 10, 1)


def train100(n=1024):
    return _reader(n, 100, 2)


def test100(n=256):
    return _reader(n, 100, 3)
