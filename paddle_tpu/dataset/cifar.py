"""CIFAR readers — reference python/paddle/dataset/cifar.py.

Parses the REAL cifar-python archive format (a tar/tar.gz of pickled
batch dicts: `data` [N, 3072] uint8 rows, `labels`/`fine_labels`) when
pointed at a local file via `data_file=`; zero-egress means no download,
so without a path the readers fall back to labeled synthetic samples with
the same shapes/ranges. Samples match the reference contract: float32
pixels in [0, 1] (3072-vector), int label.
"""
import pickle
import tarfile

import numpy as np

__all__ = ["train10", "test10", "train100", "test100", "reader_creator"]


def reader_creator(data_file, sub_name, cycle=False):
    """Yield (pixels [3072] float32 in [0,1], int label) from every
    archive member whose name contains `sub_name` (reference
    cifar.py:reader_creator — 'data_batch', 'test_batch', 'train',
    'test')."""

    def read_batch(batch):
        data = batch[b"data"]
        labels = batch.get(b"labels", batch.get(b"fine_labels"))
        assert labels is not None, "batch has neither labels nor fine_labels"
        for sample, label in zip(data, labels):
            yield sample.astype("float32") / 255.0, int(label)

    def reader():
        while True:
            matched = 0
            # archive order: gzip tars re-inflate from 0 on backward seeks
            with tarfile.open(data_file, mode="r") as f:
                for member in f:
                    if sub_name not in member.name:
                        continue
                    matched += 1
                    batch = pickle.loads(f.extractfile(member).read(),
                                         encoding="bytes")
                    yield from read_batch(batch)
            if not matched:
                raise ValueError(
                    f"no member matching {sub_name!r} in {data_file}")
            if not cycle:
                break
    return reader


def _synthetic(n, classes, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield rng.rand(3072).astype("float32"), int(rng.randint(0, classes))
    return read


def train10(n=1024, data_file=None, cycle=False):
    if data_file:
        return reader_creator(data_file, "data_batch", cycle)
    return _synthetic(n, 10, 0)


def test10(n=256, data_file=None, cycle=False):
    if data_file:
        return reader_creator(data_file, "test_batch", cycle)
    return _synthetic(n, 10, 1)


def train100(n=1024, data_file=None, cycle=False):
    if data_file:
        return reader_creator(data_file, "train", cycle)
    return _synthetic(n, 100, 2)


def test100(n=256, data_file=None, cycle=False):
    if data_file:
        return reader_creator(data_file, "test", cycle)
    return _synthetic(n, 100, 3)
