"""reference python/paddle/dataset/wmt16.py reader API — delegates to
paddle_tpu.text.WMT16 (wmt14-layout archives; see text/__init__.py)."""
from ..text import WMT16 as _WMT16

__all__ = ["train", "test", "validation", "get_dict"]


def _swap(src, trg, nxt):
    """Reverse the language direction (reference src_lang='de'): the
    stored sample is (src+<s>/<e> framing, <s>+trg, trg+<e>); the
    swapped source is <s>+trg+<e> and the swapped target pair comes
    from the inner src tokens."""
    import numpy as np
    new_src = np.concatenate([trg[:1], nxt])
    inner = src[1:-1]
    return new_src, np.concatenate([src[:1], inner]), \
        np.concatenate([inner, src[-1:]])


def _reader(mode, src_dict_size, trg_dict_size, src_lang, data_file):
    def read():
        ds = _WMT16(data_file=data_file, mode=mode,
                    src_dict_size=src_dict_size if data_file else -1,
                    trg_dict_size=trg_dict_size if data_file else -1)
        for i in range(len(ds)):
            sample = ds[i]
            yield sample if src_lang == "en" else _swap(*sample)
    return read


def train(src_dict_size=30000, trg_dict_size=30000, src_lang="en",
          data_file=None):
    return _reader("train", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def test(src_dict_size=30000, trg_dict_size=30000, src_lang="en",
         data_file=None):
    return _reader("test", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def validation(src_dict_size=30000, trg_dict_size=30000, src_lang="en",
               data_file=None):
    return _reader("val", src_dict_size, trg_dict_size, src_lang,
                   data_file)


def get_dict(lang, dict_size=30000, reverse=False, data_file=None):
    ds = _WMT16(data_file=data_file, mode="train",
                src_dict_size=dict_size if data_file else -1,
                trg_dict_size=dict_size if data_file else -1)
    d = ds.src_dict if lang == "en" else ds.trg_dict
    return {v: k for k, v in d.items()} if reverse else d
