"""MNIST readers — reference python/paddle/dataset/mnist.py.

Parses the REAL gzipped IDX format (big-endian: images magic 2051 with
[n, rows, cols], labels magic 2049) when given local `image_path`/
`label_path`; synthetic fallback otherwise (zero egress). Samples match
the reference contract: float32 pixels normalized to [-1, 1]
(784-vector), int label.
"""
import gzip
import struct

import numpy as np

__all__ = ["train", "test", "reader_creator"]


def reader_creator(image_path, label_path):
    def reader():
        with gzip.GzipFile(image_path, "rb") as f:
            img_buf = f.read()
        with gzip.GzipFile(label_path, "rb") as f:
            lab_buf = f.read()
        magic_img, n_img, rows, cols = struct.unpack_from(">IIII", img_buf, 0)
        magic_lab, n_lab = struct.unpack_from(">II", lab_buf, 0)
        if magic_img != 2051 or magic_lab != 2049:
            raise ValueError(
                f"not IDX files: image magic {magic_img} (want 2051), "
                f"label magic {magic_lab} (want 2049)")
        if n_img != n_lab:
            raise ValueError(f"{n_img} images vs {n_lab} labels")
        images = np.frombuffer(img_buf, np.uint8, n_img * rows * cols,
                               struct.calcsize(">IIII"))
        images = images.reshape(n_img, rows * cols).astype("float32")
        images = images / 255.0 * 2.0 - 1.0       # reference [-1, 1] range
        labels = np.frombuffer(lab_buf, np.uint8, n_lab,
                               struct.calcsize(">II"))
        for i in range(n_img):
            yield images[i], int(labels[i])
    return reader


def _synthetic(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield rng.rand(784).astype("float32") * 2 - 1, int(rng.randint(0, 10))
    return read


def train(n=1024, image_path=None, label_path=None):
    if image_path and label_path:
        return reader_creator(image_path, label_path)
    return _synthetic(n, 0)


def test(n=256, image_path=None, label_path=None):
    if image_path and label_path:
        return reader_creator(image_path, label_path)
    return _synthetic(n, 1)
