"""reference python/paddle/dataset/mnist.py reader API (synthetic)."""
import numpy as np

__all__ = ["train", "test"]


def _reader(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img = rng.rand(784).astype("float32") * 2 - 1
            yield img, int(rng.randint(0, 10))
    return read


def train(n=1024):
    return _reader(n, 0)


def test(n=256):
    return _reader(n, 1)
