"""Legacy paddle.dataset namespace — reference python/paddle/dataset/*.

The reference downloads real corpora; this environment is egress-free, so
each loader yields deterministic synthetic samples with the right shapes
and dtypes (same contract the reference's readers expose). The modern path
is paddle_tpu.vision.datasets / paddle_tpu.text with io.DataLoader.
"""
from . import cifar, common, imdb, mnist, uci_housing  # noqa: F401

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "common"]
