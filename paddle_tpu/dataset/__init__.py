"""Legacy paddle.dataset namespace — reference python/paddle/dataset/*.

The reference downloads real corpora; this environment is egress-free, so
each loader yields deterministic synthetic samples with the right shapes
and dtypes (same contract the reference's readers expose). The modern path
is paddle_tpu.vision.datasets / paddle_tpu.text with io.DataLoader.
"""
from . import (cifar, common, conll05, flowers, imdb,  # noqa: F401
               imikolov, mnist, movielens, uci_housing, voc2012,
               wmt14, wmt16)

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "common",
           "conll05", "flowers", "imikolov", "movielens", "voc2012",
           "wmt14", "wmt16"]
