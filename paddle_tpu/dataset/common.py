"""reference python/paddle/dataset/common.py (download/cache helpers)."""
import hashlib
import os

__all__ = ["DATA_HOME", "md5file", "download"]

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        "paddle_tpu.dataset runs egress-free: loaders yield synthetic data "
        "and never download. Point io.DataLoader at local files instead.")
