"""reference python/paddle/dataset/movielens.py reader API — delegates
to the real ml-1m parser in paddle_tpu.text.Movielens."""
from ..text import Movielens as _Movielens

__all__ = ["train", "test", "get_movie_title_dict", "movie_categories",
           "max_movie_id", "max_user_id"]


_CACHE = {}


def _ds(mode="train", data_file=None):
    key = (mode, data_file)
    if key not in _CACHE:
        _CACHE[key] = _Movielens(data_file=data_file, mode=mode)
    return _CACHE[key]


def _reader(mode, data_file):
    def read():
        ds = _ds(mode, data_file)
        for i in range(len(ds)):
            yield ds[i]
    return read


def train(data_file=None):
    return _reader("train", data_file)


def test(data_file=None):
    return _reader("test", data_file)


def get_movie_title_dict(data_file=None):
    return _ds(data_file=data_file).movie_title_dict


def movie_categories(data_file=None):
    return _ds(data_file=data_file).categories_dict


def max_movie_id(data_file=None):
    # full movies.dat table where available (reference semantics: ids
    # present only in the other split or unrated still count)
    ds = _ds(data_file=data_file)
    if getattr(ds, "max_movie_id_", None) is not None:
        return ds.max_movie_id_
    return max(int(row[4]) for row in ds.data)


def max_user_id(data_file=None):
    ds = _ds(data_file=data_file)
    if getattr(ds, "max_user_id_", None) is not None:
        return ds.max_user_id_
    return max(int(row[0]) for row in ds.data)
