"""reference python/paddle/dataset/imdb.py reader API (synthetic)."""
import numpy as np

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5149  # reference imdb vocab size ballpark


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(8, 64))
            ids = rng.randint(0, _VOCAB, (length,)).tolist()
            yield ids, int(rng.randint(0, 2))
    return read


def train(word_idx=None, n=512):
    return _reader(n, 0)


def test(word_idx=None, n=128):
    return _reader(n, 1)
