"""IMDB sentiment readers — reference python/paddle/dataset/imdb.py.

Parses the REAL aclImdb archive layout (tar/tar.gz with
aclImdb/{train,test}/{pos,neg}/*.txt review files) when given a local
`data_file=`; builds the frequency-sorted word dict from the training
corpus like the reference (tokenize: lowercase, strip punctuation,
whitespace split). Synthetic fallback otherwise (zero egress). Samples:
(word-id list, label) with label 0=negative, 1=positive.
"""
import re
import string
import tarfile

import numpy as np

__all__ = ["train", "test", "word_dict", "build_dict", "tokenize"]

_VOCAB = 5149  # synthetic fallback vocab size (reference ballpark)


def tokenize(text):
    """Reference imdb.py tokenize: drop punctuation, lowercase, split."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="replace")
    return text.translate(
        str.maketrans("", "", string.punctuation)).lower().split()


def _corpus(data_file, pattern):
    # ARCHIVE order, not sorted: a gzip tar can only stream forward, and
    # out-of-order extractfile() seeks re-inflate from byte 0 each time
    rx = re.compile(pattern)
    with tarfile.open(data_file, mode="r") as f:
        for member in f:
            if rx.match(member.name):
                yield tokenize(f.extractfile(member).read())


def build_dict(data_file, pattern=r"aclImdb/train/(pos|neg)/.*\.txt$",
               cutoff=0):
    """Frequency-sorted {word: id} from the training corpus (reference
    imdb.py:build_dict); id len(dict) is reserved for OOV ('<unk>')."""
    freq = {}
    for words in _corpus(data_file, pattern):
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    items = sorted(((c, w) for w, c in freq.items() if c > cutoff),
                   key=lambda cw: (-cw[0], cw[1]))
    word_idx = {w: i for i, (_, w) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)   # reference reserves the last id
    return word_idx


def _real_reader(data_file, word_idx, split):
    unk = word_idx.get("<unk>", len(word_idx))
    neg = re.compile(rf"aclImdb/{split}/neg/.*\.txt$")
    pos = re.compile(rf"aclImdb/{split}/pos/.*\.txt$")

    def read():
        # ONE forward tar traversal for both classes, in archive order
        # (gzip tars re-inflate from 0 on any backward seek)
        with tarfile.open(data_file, mode="r") as f:
            for member in f:
                name = member.name
                label = 1 if pos.match(name) else (0 if neg.match(name)
                                                   else None)
                if label is None:
                    continue
                words = tokenize(f.extractfile(member).read())
                yield [word_idx.get(w, unk) for w in words], label
    return read


def word_dict(data_file=None):
    if data_file:
        return build_dict(data_file)
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(8, 64))
            yield rng.randint(0, _VOCAB, (length,)).tolist(), \
                int(rng.randint(0, 2))
    return read


def train(word_idx=None, n=512, data_file=None):
    if data_file:
        return _real_reader(data_file, word_idx or build_dict(data_file),
                            "train")
    return _synthetic(n, 0)


def test(word_idx=None, n=128, data_file=None):
    if data_file:
        return _real_reader(data_file, word_idx or build_dict(data_file),
                            "test")
    return _synthetic(n, 1)
