"""reference python/paddle/dataset/imikolov.py reader API — delegates to
the real PTB parser in paddle_tpu.text.Imikolov (data_file= points at a
local simple-examples tarball; synthetic fallback otherwise)."""
from ..text import Imikolov as _Imikolov

__all__ = ["build_dict", "train", "test"]

N = 5  # reference default ngram order


def build_dict(min_word_freq=50, data_file=None):
    return _Imikolov(data_file=data_file, data_type="NGRAM",
                     window_size=N, min_word_freq=min_word_freq).word_idx


def _reader(word_idx, n, mode, data_file, min_word_freq):
    def read():
        ds = _Imikolov(data_file=data_file, data_type="NGRAM",
                       window_size=n, mode=mode,
                       min_word_freq=min_word_freq)
        if word_idx is not None and len(word_idx) != len(ds.word_idx):
            raise ValueError(
                f"word_idx has {len(word_idx)} entries but the corpus "
                f"dict (min_word_freq={min_word_freq}) has "
                f"{len(ds.word_idx)} — build both with the same "
                "min_word_freq/data_file")
        for i in range(len(ds)):
            yield tuple(int(x) for x in ds[i])
    return read


def train(word_idx=None, n=N, data_file=None, min_word_freq=50):
    return _reader(word_idx, n, "train", data_file, min_word_freq)


def test(word_idx=None, n=N, data_file=None, min_word_freq=50):
    return _reader(word_idx, n, "test", data_file, min_word_freq)
