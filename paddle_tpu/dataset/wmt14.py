"""reference python/paddle/dataset/wmt14.py reader API — delegates to
the real parser in paddle_tpu.text.WMT14."""
from ..text import WMT14 as _WMT14

__all__ = ["train", "test", "gen", "get_dict"]


def _reader(mode, dict_size, data_file):
    def read():
        ds = _WMT14(data_file=data_file, mode=mode,
                    dict_size=dict_size if data_file else -1)
        for i in range(len(ds)):
            yield ds[i]
    return read


def train(dict_size=30000, data_file=None):
    return _reader("train", dict_size, data_file)


def test(dict_size=30000, data_file=None):
    return _reader("test", dict_size, data_file)


def gen(dict_size=30000, data_file=None):
    return _reader("gen", dict_size, data_file)


def get_dict(dict_size=30000, reverse=True, data_file=None):
    ds = _WMT14(data_file=data_file, mode="train",
                dict_size=dict_size if data_file else -1)
    src, trg = ds.src_dict, ds.trg_dict
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
