"""reference python/paddle/dataset/flowers.py reader API — delegates to
vision.datasets.Flowers for real archives (102flowers.tgz +
imagelabels.mat + setid.mat local paths); synthetic fallback otherwise."""
import numpy as np

__all__ = ["train", "test", "valid"]


def _reader(mode, n, files, mapper=None, cycle=False):
    def one_pass():
        if files.get("data_file"):
            from ..vision.datasets import Flowers
            ds = Flowers(mode=mode, **files)
            for i in range(len(ds)):
                img, label = ds[i]
                yield np.asarray(img), int(label)
            return
        rng = np.random.RandomState(
            {"train": 0, "test": 1, "valid": 2}[mode])
        for _ in range(n):
            yield rng.rand(3 * 32 * 32).astype("float32"), \
                int(rng.randint(0, 102))

    def read():
        while True:
            for sample in one_pass():
                yield mapper(sample) if mapper is not None else sample
            if not cycle:
                break
    return read


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
          n=256, **files):
    return _reader("train", n, files, mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
         n=64, **files):
    return _reader("test", n, files, mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True, n=64, **files):
    return _reader("valid", n, files, mapper)
