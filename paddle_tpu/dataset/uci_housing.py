"""reference python/paddle/dataset/uci_housing.py reader API (synthetic
13-feature regression with a fixed linear ground truth + noise)."""
import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

_W = np.linspace(-1.0, 1.0, 13).astype("float32")


def _reader(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = rng.rand(13).astype("float32")
            y = np.array([float(x @ _W) + rng.randn() * 0.01], "float32")
            yield x, y
    return read


def train(n=404):
    return _reader(n, 0)


def test(n=102):
    return _reader(n, 1)
