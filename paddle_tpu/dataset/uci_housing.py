"""UCI housing readers — reference python/paddle/dataset/uci_housing.py.

Parses the REAL housing.data format (whitespace-separated table, 13
features + MEDV target per row) from a local `data_file=`, with the
reference's feature normalization: (x - mean) scaled by the max-min
range, computed over the whole table, then an 80/20 train/test split
(reference uci_housing.py: load_data ratio=0.8). Synthetic linear
regression fallback otherwise (zero egress).
"""
import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

_W = np.linspace(-1.0, 1.0, 13).astype("float32")


def _load(data_file):
    data = np.loadtxt(data_file).astype("float32")
    if data.ndim == 1:
        data = data.reshape(-1, 14)
    if data.shape[1] != 14:
        raise ValueError(
            f"housing.data rows must have 14 columns, got {data.shape[1]}")
    feats = data[:, :13]
    # reference normalization: (x - mean) / (max - min) per feature
    span = feats.max(0) - feats.min(0)
    feats = (feats - feats.mean(0)) / np.where(span == 0, 1.0, span)
    return feats, data[:, 13:14]


def _real_reader(data_file, is_train, ratio=0.8):
    def read():
        feats, target = _load(data_file)
        split = int(len(feats) * ratio)
        sl = slice(0, split) if is_train else slice(split, None)
        for x, y in zip(feats[sl], target[sl]):
            yield x, y
    return read


def _synthetic(n, seed):
    def read():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = rng.rand(13).astype("float32")
            y = np.array([float(x @ _W) + rng.randn() * 0.01], "float32")
            yield x, y
    return read


def train(n=404, data_file=None):
    if data_file:
        return _real_reader(data_file, True)
    return _synthetic(n, 0)


def test(n=102, data_file=None):
    if data_file:
        return _real_reader(data_file, False)
    return _synthetic(n, 1)
