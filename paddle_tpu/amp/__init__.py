"""AMP — reference python/paddle/amp/{auto_cast,grad_scaler}.py.

On TPU the native mixed-precision story is bf16: auto_cast('bfloat16')
casts op inputs at the dispatch layer (O1-style allowlist) or whole layers
(O2). GradScaler keeps fp16 API parity; with bf16 it is a functional no-op
(scale 1) since bf16 shares fp32's exponent range.
"""
import contextlib

import jax.numpy as jnp

from ..framework import core as _core
from ..framework.core import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate"]

# ops that are numerically safe & profitable in low precision (mirrors the
# reference's white list in fluid/contrib/mixed_precision/fp16_lists.py)
_FP16_WHITELIST_HINT = {"matmul", "conv2d", "einsum"}

_amp_state = {"enabled": False, "dtype": jnp.bfloat16, "level": "O1"}


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = dict(_amp_state)
    _amp_state.update(enabled=enable, dtype=jnp.dtype(dtype), level=level)
    try:
        yield
    finally:
        _amp_state.update(prev)


amp_guard = auto_cast


def amp_enabled():
    return _amp_state["enabled"]


def amp_dtype():
    return _amp_state["dtype"]


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to bf16; optimizer keeps fp32 masters."""
    d = jnp.dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.astype(d)
    if optimizers is None:
        return models if single else model_list
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for o in opt_list:
        o._multi_precision = True
    return (models if single else model_list), (optimizers if opt_single else opt_list)


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import numpy as np
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = p.grad._value * inv
                p.grad._value = g
                found = found or bool(jnp.any(~jnp.isfinite(g)))
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good": self._good, "bad": self._bad}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good = state.get("good", 0)
        self._bad = state.get("bad", 0)
