"""paddle_tpu.device — reference python/paddle/device/__init__.py."""
from ..framework.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    CustomPlace,
    IPUPlace,
    MLUPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)

__all__ = ["set_device", "get_device", "device_count", "TPUPlace", "CPUPlace",
           "CustomPlace", "IPUPlace", "MLUPlace", "XPUPlace",
           "is_compiled_with_cuda", "is_compiled_with_tpu",
           "is_compiled_with_cinn", "is_compiled_with_ipu",
           "is_compiled_with_mlu", "is_compiled_with_npu",
           "is_compiled_with_rocm", "is_compiled_with_xpu",
           "get_cudnn_version", "get_all_custom_device_type",
           "get_available_custom_device", "get_all_device_type",
           "get_available_device"]


def is_compiled_with_rocm():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def get_cudnn_version():
    """No cuDNN in a TPU build (reference returns None when not compiled)."""
    return None


def get_all_custom_device_type():
    return []


def get_available_custom_device():
    return []


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


class cuda:
    """Namespace parity for paddle.device.cuda on TPU builds."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()
