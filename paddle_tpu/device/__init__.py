"""paddle_tpu.device — reference python/paddle/device/__init__.py."""
from ..framework.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    CustomPlace,
    IPUPlace,
    MLUPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)

__all__ = ["set_device", "get_device", "device_count", "TPUPlace", "CPUPlace",
           "CustomPlace", "IPUPlace", "MLUPlace", "XPUPlace",
           "is_compiled_with_cuda", "is_compiled_with_tpu",
           "memory_allocated", "max_memory_allocated", "memory_reserved",
           "max_memory_reserved",
           "is_compiled_with_cinn", "is_compiled_with_ipu",
           "is_compiled_with_mlu", "is_compiled_with_npu",
           "is_compiled_with_rocm", "is_compiled_with_xpu",
           "get_cudnn_version", "get_all_custom_device_type",
           "get_available_custom_device", "get_all_device_type",
           "get_available_device"]


def is_compiled_with_rocm():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def get_cudnn_version():
    """No cuDNN in a TPU build (reference returns None when not compiled)."""
    return None


def get_all_custom_device_type():
    return []


def get_available_custom_device():
    return []


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def _memory_stats(device=None):
    """PJRT per-device memory stats ({} when the backend exposes none —
    CPU does; TPU reports bytes_in_use/peak_bytes_in_use/bytes_limit).
    Invalid device ids raise (reference paddle.device.cuda behavior) —
    only a stats-less backend degrades to zeros."""
    import jax
    idx = 0
    if isinstance(device, str) and ":" in device:
        idx = int(device.split(":")[1])
    elif isinstance(device, int):
        idx = device
    devices = jax.local_devices()
    if not 0 <= idx < len(devices):
        raise ValueError(
            f"invalid device index {idx}: {len(devices)} local device(s)")
    try:
        return devices[idx].memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    """Live device-memory bytes (reference
    paddle.device.cuda.memory_allocated; PJRT bytes_in_use here)."""
    return int(_memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    """Peak device-memory bytes (PJRT peak_bytes_in_use)."""
    return int(_memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    """Total allocator pool (PJRT pool_bytes, else bytes_limit)."""
    st = _memory_stats(device)
    return int(st.get("pool_bytes", st.get("bytes_limit", 0)))


def max_memory_reserved(device=None):
    """Peak allocator pool (PJRT peak_pool_bytes where exposed; pools
    that never shrink fall back to the current/limit figures)."""
    st = _memory_stats(device)
    return int(st.get("peak_pool_bytes",
                      st.get("peak_bytes_reserved",
                             st.get("pool_bytes",
                                    st.get("bytes_limit", 0)))))


class cuda:
    """Namespace parity for paddle.device.cuda on TPU builds (memory
    queries answer for the actual accelerator via PJRT memory_stats)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()

    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
