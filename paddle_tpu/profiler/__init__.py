"""Profiler — reference python/paddle/profiler. Wraps jax.profiler (perfetto
trace viewable in XProf/TensorBoard) plus lightweight host-side timers."""
import contextlib
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "RecordEvent", "profiler_guard", "export_chrome_tracing"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "tpu"  # alias: reference name kept for API parity
    TPU = "tpu"


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._events = []
        self._started = False

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        self._t0 = time.perf_counter()
        self._started = True

    def stop(self):
        if self._started and not self.timer_only:
            jax.profiler.stop_trace()
        self._started = False

    def step(self, num_samples=None):
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        return f"trace written to {self.log_dir}" if not self.timer_only else "timer-only run"

    def export(self, path=None, format="json"):
        return self.log_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Annotates a named region (shows up in XLA trace via named_scope)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._scope = jax.named_scope(name)

    def begin(self):
        self._scope.__enter__()

    def end(self):
        self._scope.__exit__(None, None, None)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def profiler_guard(log_dir="./profiler_log"):
    p = Profiler(log_dir=log_dir)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        return dir_name
    return handler


def load_profiler_result(filename):
    raise NotImplementedError("load exported traces with XProf/TensorBoard")
