"""Profiler — reference python/paddle/profiler (profiler.py, timer.py,
profiler_statistic.py).

Three measurement layers, all real:

- device traces: jax.profiler start/stop_trace (perfetto, viewable in
  XProf/TensorBoard) around the RECORD states of the scheduler;
- host timers: per-step durations (Profiler.step), named regions
  (RecordEvent), and — while a profiler is active — per-op eager dispatch
  timings hooked into framework.core.apply_op (the TPU rendering of the
  reference's op-level CPU/GPU time tables);
- summary()/export(): aggregated statistics table / chrome-trace JSON.
"""
import contextlib
import json
import os
import threading
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "RecordEvent", "profiler_guard",
           "export_chrome_tracing", "make_scheduler", "ProfilerState",
           "SortedKeys", "export_protobuf", "load_profiler_result"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "tpu"  # alias: reference name kept for API parity
    TPU = "tpu"


class ProfilerState:
    """Reference python/paddle/profiler/profiler.py:ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    """Reference python/paddle/profiler/profiler.py:SortedKeys."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Build a step-state schedule fn — reference profiler.make_scheduler."""
    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


class _Stat:
    __slots__ = ("count", "total", "mx", "mn")

    def __init__(self):
        self.count, self.total = 0, 0.0
        self.mx, self.mn = 0.0, float("inf")

    def add(self, dt):
        self.count += 1
        self.total += dt
        self.mx = max(self.mx, dt)
        self.mn = min(self.mn, dt)


_tls = threading.local()


def _event_stack():
    if not hasattr(_tls, "events"):
        _tls.events = []
    return _tls.events


_active_profiler = None    # host-timer sink (independent of the op hook)


class Profiler:
    """Measures while active: step durations, RecordEvent regions, per-op
    eager dispatch times; optionally records a jax device trace."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir="./profiler_log", record_ops=True):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.record_ops = record_ops
        self.on_trace_ready = on_trace_ready
        if isinstance(scheduler, tuple):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=max(lo, 0), ready=0,
                                       record=hi - lo, repeat=1)
        self._scheduler = scheduler
        self._step_idx = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._started = False
        self._step_stat = _Stat()
        self._event_stats = {}
        self._op_stats = {}
        self._timeline = []         # (name, start_s, dur_s) host events
        self._step_t0 = None
        self._num_samples = 0
        self._pending_cycle = False    # recorded data not yet handed to handler

    # -- op hook (called from framework.core.apply_op) --------------------
    def _record_op(self, name, t0, t1):
        stack = _event_stack()
        if stack:
            name = f"{stack[-1]}::{name}"
        self._op_stats.setdefault(name, _Stat()).add(t1 - t0)

    def _record_event(self, name, t0, t1):
        if not self._recording_now():
            return
        self._event_stats.setdefault(name, _Stat()).add(t1 - t0)
        self._timeline.append((name, t0, t1 - t0))

    # -- lifecycle --------------------------------------------------------
    def start(self):
        global _active_profiler
        self._started = True
        self._wall0 = time.perf_counter()
        self._step_t0 = time.perf_counter()
        _active_profiler = self
        if self._scheduler is None:
            self._pending_cycle = True
            self._set_op_hook(True)
            if not self.timer_only:
                self._start_trace()
        else:
            self._apply_state(self._scheduler(self._step_idx))

    def _recording_now(self):
        return self._scheduler is None or self._state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)

    def stop(self):
        global _active_profiler
        if not self._started:
            return
        self._set_op_hook(False)
        if _active_profiler is self:
            _active_profiler = None
        if self._tracing:
            self._stop_trace()
        self._started = False
        if self.on_trace_ready is not None and self._pending_cycle:
            self.on_trace_ready(self)
            self._pending_cycle = False

    def _set_op_hook(self, on):
        """The op hook syncs the device per dispatch (honest timings), so it
        is only installed while the scheduler is in a RECORD state."""
        from ..framework import core
        if on and self.record_ops and not self.timer_only:
            core._op_profiler = self
        elif core._op_profiler is self:
            core._op_profiler = None

    def _start_trace(self):
        try:
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True
            self._trace_ran = True
        except Exception:
            self._tracing = False

    def _stop_trace(self):
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._tracing = False

    def _apply_state(self, state):
        recording = state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        self._set_op_hook(recording)
        if recording and not self._tracing and not self.timer_only:
            self._start_trace()
        elif not recording and self._tracing:
            self._stop_trace()
        if recording:
            self._pending_cycle = True
        if self._state == ProfilerState.RECORD_AND_RETURN and not recording \
                and self.on_trace_ready is not None:
            self.on_trace_ready(self)      # cycle boundary (reference behavior)
            self._pending_cycle = False
        self._state = state

    def step(self, num_samples=None):
        """Marks a training-step boundary: times the step, advances the
        trace scheduler."""
        now = time.perf_counter()
        if self._step_t0 is not None and self._recording_now():
            self._step_stat.add(now - self._step_t0)
            self._timeline.append((f"step#{self._step_idx}", self._step_t0,
                                   now - self._step_t0))
        self._step_t0 = now
        if num_samples:
            self._num_samples += num_samples
        self._step_idx += 1
        if self._scheduler is not None:
            self._apply_state(self._scheduler(self._step_idx))

    def step_info(self, unit=None):
        s = self._step_stat
        if s.count == 0:
            return "no steps recorded"
        avg = s.total / s.count
        ips = (self._num_samples / s.total) if s.total and self._num_samples else 0.0
        return (f"batch_cost: {avg * 1000:.2f} ms, ips: {ips:.2f} samples/s"
                if ips else f"batch_cost: {avg * 1000:.2f} ms")

    # -- reporting --------------------------------------------------------
    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)

        def table(title, stats):
            if not stats:
                return ""
            rows = sorted(stats.items(), key=lambda kv: -kv[1].total)
            w = max(28, max(len(k) for k in stats) + 2)
            head = (f"\n{title}\n" + "-" * (w + 48) + "\n"
                    + f"{'Name':<{w}}{'Calls':>7}{'Total':>12}{'Avg':>10}"
                    + f"{'Max':>10}{'Min':>9}  ({time_unit})\n")
            body = "".join(
                f"{k:<{w}}{st.count:>7}{st.total * unit:>12.3f}"
                f"{st.total / st.count * unit:>10.3f}{st.mx * unit:>10.3f}"
                f"{st.mn * unit:>9.3f}\n"
                for k, st in rows[:60])
            return head + body

        out = ["Profiler summary"]
        if self._step_stat.count:
            out.append(table("Steps", {"train_step": self._step_stat}))
            out.append(self.step_info() + "\n")
        out.append(table("Events (RecordEvent)", self._event_stats))
        if op_detail:
            out.append(table("Ops (eager dispatch, host)", self._op_stats))
        if getattr(self, "_trace_ran", False):
            out.append(f"device trace dir: {self.log_dir}\n")
        return "".join(o for o in out if o)

    def timeline_events(self):
        """The host timeline (RecordEvent regions, step marks) as
        chrome-trace event dicts, ts-sorted so the (0, 0) track is
        monotonic (a nested region is APPENDED at its end time, so raw
        timeline order is end-time order — a child's later start would
        precede its parent's earlier one). Timestamps are raw
        perf_counter microseconds, the same base
        `serving.trace.FlightRecorder` stamps — `serving.trace
        .export_chrome_trace` merges both onto one timeline."""
        return sorted(
            ({"name": n, "ph": "X", "ts": t0 * 1e6, "dur": d * 1e6,
              "pid": 0, "tid": 0} for n, t0, d in self._timeline),
            key=lambda e: e["ts"])

    def export(self, path=None, format="json"):
        """Writes the host timeline as a chrome-trace JSON (load with
        load_profiler_result / chrome://tracing); returns the path."""
        path = path or os.path.join(self.log_dir, "host_trace.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self.timeline_events()}, f)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Named region: shows in the XLA trace via named_scope AND is host-timed
    into the active Profiler's event table."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._scope = jax.named_scope(name)

    def begin(self):
        self._t0 = time.perf_counter()
        _event_stack().append(self.name)
        self._scope.__enter__()

    def end(self):
        self._scope.__exit__(None, None, None)
        stack = _event_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        t1 = time.perf_counter()
        if _active_profiler is not None and hasattr(self, "_t0"):
            _active_profiler._record_event(self.name, self._t0, t1)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def profiler_guard(log_dir="./profiler_log"):
    p = Profiler(log_dir=log_dir)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        return prof.export(os.path.join(dir_name, "host_trace.json"))
    return handler


def export_protobuf(dir_name, worker_name=None):
    """Exporter callback (jax.profiler writes its own pb into log_dir)."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        return dir_name
    return handler


def load_profiler_result(filename):
    """Loads a chrome-trace JSON written by Profiler.export."""
    with open(filename) as f:
        return json.load(f)
