"""Profiler — reference python/paddle/profiler. Wraps jax.profiler (perfetto
trace viewable in XProf/TensorBoard) plus lightweight host-side timers."""
import contextlib
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "RecordEvent", "profiler_guard", "export_chrome_tracing"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "tpu"  # alias: reference name kept for API parity
    TPU = "tpu"


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._events = []
        self._started = False

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        self._t0 = time.perf_counter()
        self._started = True

    def stop(self):
        if self._started and not self.timer_only:
            jax.profiler.stop_trace()
        self._started = False

    def step(self, num_samples=None):
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        return f"trace written to {self.log_dir}" if not self.timer_only else "timer-only run"

    def export(self, path=None, format="json"):
        return self.log_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Annotates a named region (shows up in XLA trace via named_scope)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._scope = jax.named_scope(name)

    def begin(self):
        self._scope.__enter__()

    def end(self):
        self._scope.__exit__(None, None, None)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def profiler_guard(log_dir="./profiler_log"):
    p = Profiler(log_dir=log_dir)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        return dir_name
    return handler


def load_profiler_result(filename):
    raise NotImplementedError("load exported traces with XProf/TensorBoard")


class ProfilerState:
    """Reference python/paddle/profiler/profiler.py:ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys:
    """Reference python/paddle/profiler/profiler.py:SortedKeys."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Build a step-state schedule fn — reference profiler_statistic scheduler."""
    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_protobuf(dir_name, worker_name=None):
    """Exporter callback (serialized trace; jax.profiler emits its own pb)."""
    def handler(prof):
        import os
        os.makedirs(dir_name, exist_ok=True)
        return dir_name
    return handler
