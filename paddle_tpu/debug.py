"""Failure detection & numerics debugging.

Reference counterpart: FLAGS_check_nan_inf + paddle/fluid/framework/details/
nan_inf_utils (per-op NaN/Inf scan). TPU-native: a jit-compatible checker
based on jax error-checking semantics — `check_numerics` inserts a device-side
assert-like guard; `enable_check_nan_inf` flips a global that the Trainer and
eager dispatch honor on loss/grads.
"""
import jax
import jax.numpy as jnp

from .framework.core import Tensor, apply_op

__all__ = ["check_numerics", "enable_check_nan_inf", "check_nan_inf_enabled",
           "assert_finite_pytree", "TensorCheckerConfig", "diagnose",
           "input_pipeline_stats", "memory_report", "schedule_report",
           "determinism_report",
           "autotune", "serving_stats", "serving_report"]


def serving_stats():
    """Telemetry of every live serving engine
    (`serving.ContinuousBatchingEngine` / `SpeculativeEngine`): queue
    wait, slot occupancy, tokens/s, per-token p50/p99 latency, and —
    the multi-step decode headline — host syncs per generated token
    (1.0 on the per-tick path, ≤ 1/K with a K-tick horizon). The
    observability half of device-resident decode: when
    `host_syncs_per_token` is near 1 on a model whose tick roofline is
    tiny, the host round-trip (not the chip) is the decode bottleneck —
    raise the engine's `k_max` or let `cost_model.decode_horizon` price
    it. Returns one summary dict per engine."""
    from .serving import serving_stats as _stats
    return _stats()


def serving_report(drift_factor=None, print_report=False):
    """Deep serving observability, one dict per live engine (sorted by
    engine name/id like `serving_stats`): the `ServeStats` summary,
    the recent scheduling-decision trace summarized (horizons,
    prefill syncs, stalls), and — when the engine carries a flight
    recorder (`ContinuousBatchingEngine(trace=...)`) — the rolling
    roofline-drift ledger per dispatch shape with its mispriced
    shapes flagged (`serving.trace.FlightRecorder.drift_report`; the
    CI face of the same data is the `ROOFLINE-DRIFT` Graph Doctor
    rule). When `host_syncs_per_token` says the host interposes too
    often, this report says WHICH horizon shapes and WHY: drift > 1
    means ticks run slower than priced (the scheduler fuses too few
    ticks per sync), drift < 1 that the model overprices and leaves
    capacity scheduled idle."""
    from .serving.stats import live_engines
    report = []
    for eng in live_engines():
        entry = {"stats": eng.stats.summary()}
        if hasattr(eng, "tenancy_summary"):
            # multi-tenant engines: per-tenant ledgers, per-class p99
            # vs roofline targets, fairness, preemption counts
            entry["tenancy"] = eng.tenancy_summary()
        events = eng.serve_schedule() if hasattr(eng, "serve_schedule") \
            else []
        if events:
            entry["schedule"] = {
                "horizons": sum(ev.get("kind") == "horizon"
                                for ev in events),
                "prefill_syncs": sum(ev.get("kind") == "prefill_sync"
                                     for ev in events),
                "stalled_prefill_syncs": sum(
                    ev.get("kind") == "prefill_sync"
                    and ev.get("decode_active", 0) > 0 for ev in events),
            }
        rec = getattr(eng, "trace", None)
        if rec is not None:
            drift = rec.drift_report(factor=drift_factor)
            entry["drift"] = drift
            entry["drifting_shapes"] = [d["shape"] for d in drift
                                        if d["drifting"]]
            entry["trace_events"] = len(rec.events)
            # pad ledger over the recorder's tick window: how much of
            # the dispatched token layout was padding (the packed
            # ragged layout's before/after evidence — the lifetime
            # view lives in stats.pad_fraction; this is the recent-
            # horizon view the tick records carry)
            ticks = [ev for ev in rec.events if ev["kind"] == "tick"
                     and ev.get("tokens_dispatched")]
            disp = sum(ev["tokens_dispatched"] for ev in ticks)
            if disp:
                padded = sum(ev.get("tokens_padded") or 0
                             for ev in ticks)
                entry["pad"] = {
                    "tokens_dispatched": disp, "tokens_padded": padded,
                    "pad_fraction": round(padded / disp, 4)}
        report.append(entry)
    if print_report:
        for entry in report:
            s = entry["stats"]
            print(f"== {s['engine']}#{s['engine_id']} ==")
            for key in ("stats", "schedule", "pad"):
                if key in entry:
                    print(f"  {key}: {entry[key]}")
            for d in entry.get("drift", ()):
                flag = "  << DRIFTING" if d["drifting"] else ""
                print(f"  drift {d['shape']}: predicted "
                      f"{d['predicted_s'] * 1e3:.3f} ms measured "
                      f"{d['measured_s'] * 1e3:.3f} ms ratio "
                      f"{d['ratio']:.2f} (n={d['n']}){flag}")
    return report


def fleet_report(router, print_report=False):
    """Fleet-wide serving observability (`serving.FleetRouter`): the
    `ServeStats.merge()` summary over every replica (counters summed,
    latency windows pooled in the deterministic replica order — the
    fleet p50/p99 is the pooled math, not an average of averages),
    the merged per-tenant/SLO ledgers, per-replica one-line stats,
    and the shared host tier's occupancy. The fleet face of
    `serving_report`: when the merged `prefix_hit_rate` sits below a
    single replica's, the affinity split is fragmenting the template
    working set; when `tier.n_entries` grows while hit rate holds,
    the shared tier is absorbing an HBM cliff (docs/serving.md
    "Fleet serving")."""
    merged = router.merged_stats().summary()
    tier = getattr(router.engines[0], "cache", None)
    tier = getattr(tier, "tier", None)
    report = {
        "stats": merged,
        "tenancy": router.tenancy_summary(),
        "replicas": [e.stats.summary() for e in router.engines],
    }
    if tier is not None and getattr(tier, "shared", False):
        report["shared_tier"] = {"entries": tier.n_entries,
                                 "bytes": tier.bytes_used,
                                 "path": str(tier.path)}
    if print_report:
        print(f"== fleet of {len(router.engines)} ==")
        print(f"  merged: {merged}")
        for i, r in enumerate(report["replicas"]):
            print(f"  replica{i}: requests {r.get('requests', 0)}, "
                  f"tokens {r.get('tokens', 0)}, hit_rate "
                  f"{r.get('prefix_hit_rate', 0.0)}")
        if "shared_tier" in report:
            print(f"  shared_tier: {report['shared_tier']}")
    return report


def autotune(target, *example_inputs, batch=None, hbm_budget=None,
             print_report=True, **kw):
    """Static (microbatch, remat) autotuner — the front door of
    `paddle_tpu.analysis.autotune`. No compile, no device execution:
    one no-remat CPU trace per candidate batch size, a what-if liveness
    replay per remat policy (what the Memory Doctor's peak becomes when
    the policy's checkpointed intermediates are dropped), and a
    roofline step-time ranking (max of compute/HBM/wire time).

    `target` may be a `distributed.Trainer` (pass the training
    `batch=`; candidates cover microbatch x policy for the REAL
    compiled step) or an `nn.Layer` (pass example inputs; policy sweep
    over a synthetic grad program). Returns an
    `analysis.AutotuneReport`: `.best` is the config to measure first,
    `.advice` the per-policy "peak X → Y per device, +Z% recompute
    FLOPs" lines. `hbm_budget` (bytes) prunes configs that don't fit —
    default is the chip's HBM capacity."""
    from .analysis.autotune import autotune as _autotune, autotune_layer
    from .nn.layer_base import Layer

    # Trainer-shaped = analysis_program AND step: PagedGPTDecoder also
    # exposes analysis_program (for memory_report/lints) but has no
    # train step to tune — it must fall through to the clear TypeError
    if hasattr(target, "analysis_program") and hasattr(target, "step"):
        if batch is None:
            raise ValueError("debug.autotune(trainer) needs batch=...")
        report = _autotune(target, batch, hbm_budget=hbm_budget, **kw)
    elif isinstance(target, Layer):
        args = [x._value if isinstance(x, Tensor) else x
                for x in example_inputs]
        report = autotune_layer(target, *args, hbm_budget=hbm_budget,
                                **kw)
    else:
        raise TypeError("debug.autotune wants a Trainer or an nn.Layer, "
                        f"got {type(target).__name__}")
    if print_report:
        print(report)
    return report


def memory_report(target, *example_inputs, batch=None, lr=0.0, top_k=8,
                  print_report=True, axis_host_counts=None):
    """Static per-device HBM report, before a chip sees the program.

    `target` may be a `distributed.Trainer` (pass the training `batch`;
    the report covers the FULL compiled step — fwd+bwd+optimizer, with
    the real shardings and donation), an `nn.Layer` (pass example
    inputs; forward only), or any jittable callable. Returns the
    `analysis.MemoryEstimate`: per-device peak live bytes, the
    args/transient split, the donation credit, and the top-k live
    tensors at the peak with their defining ops — the "what do I shard,
    remat or donate to fit" answer.  Estimates use native dtype widths
    (the TPU numbers), chip-independent: lowering happens on CPU.

    `axis_host_counts` ({axis: hosts}, the schedule pass's convention)
    marks a multi-host mesh: the report then also prices the
    DISTINCT-bytes-per-host peak (dp shards replicated within a host
    counted once) — the per-host checkpoint/offload footprint of a
    dp-over-hosts layout."""
    from .analysis import estimate_jaxpr_memory
    from .analysis.lowering import lower_callable, lower_layer
    from .nn.layer_base import Layer

    if hasattr(target, "analysis_program"):
        if hasattr(target, "step"):                # Trainer-shaped
            if batch is None:
                raise ValueError("memory_report(trainer) needs batch=...")
            program = target.analysis_program(batch, lr=lr)
        else:            # decoder-shaped (PagedGPTDecoder): the program
            program = target.analysis_program()    # is self-contained
    elif isinstance(target, Layer):
        args = [x._value if isinstance(x, Tensor) else x
                for x in example_inputs]
        program = lower_layer(target, *args)
    else:
        args = [x._value if isinstance(x, Tensor) else x
                for x in example_inputs]
        program = lower_callable(target, *args)
    n_hosts = 1
    for h in (axis_host_counts or {}).values():
        n_hosts *= max(int(h), 1)
    est = estimate_jaxpr_memory(program.jaxpr,
                                arg_infos=program.arg_infos, top_k=top_k,
                                n_hosts=n_hosts)
    if print_report:
        print(f"== memory report: {program.name} ==")
        print(est)
    return est


def schedule_report(target, *example_inputs, batch=None, lr=0.0,
                    hide_frac=0.5, chip="v5e", print_report=True):
    """Overlap-aware schedule report: the two-stream (compute vs
    collective) critical path of the lowered program, before a chip
    sees it.

    `target` may be a `distributed.Trainer` (pass the training
    `batch=`; the report covers the SAME specialized step `step()`
    dispatches — real shardings, real collectives), an `nn.Layer`
    (pass example inputs), or any jittable callable. Returns the
    `analysis.ScheduleEstimate`: the bracketed step time (roofline
    max <= overlap-aware <= serial sum), the fraction of collective
    wire time the schedule hides under compute, the critical path
    with per-op source attribution, and the COLL-SERIALIZED evidence
    — collectives the lowered program cannot overlap with anything
    (`hide_frac` is the bar). The same estimate feeds
    `debug.autotune`'s step pricing and the schedule manifests the
    `lint_schedule` gate pins."""
    from .analysis import estimate_schedule
    from .analysis.lowering import lower_callable, lower_layer
    from .nn.layer_base import Layer

    if hasattr(target, "analysis_program"):
        if hasattr(target, "step"):                # Trainer-shaped
            if batch is None:
                raise ValueError(
                    "schedule_report(trainer) needs batch=...")
            program = target.analysis_program(batch, lr=lr)
        else:            # decoder-shaped (PagedGPTDecoder)
            program = target.analysis_program()
    elif isinstance(target, Layer):
        args = [x._value if isinstance(x, Tensor) else x
                for x in example_inputs]
        program = lower_layer(target, *args)
    else:
        args = [x._value if isinstance(x, Tensor) else x
                for x in example_inputs]
        program = lower_callable(target, *args)
    mesh_axes = None
    try:
        from .distributed import mesh_axis_sizes
        mesh_axes = mesh_axis_sizes()
    except Exception:
        pass
    est = estimate_schedule(program, mesh_axes=mesh_axes,
                            hide_frac=hide_frac, chip=chip)
    if print_report:
        print(f"== schedule report: {program.name} ==")
        print(est)
    return est


def determinism_report(target=None, print_report=True, thread_paths=None,
                       **program_kw):
    """Determinism Doctor front door: prove (or refute) the
    byte-identical-stream invariant statically, before a request ever
    reaches a chip.

    `target` may be a serving decoder — anything with
    `analysis_program`, e.g. `serving.PagedGPTDecoder`; `program_kw`
    forwards, so `determinism_report(dec, k=4)` audits the same fused
    multi-step program the engine dispatches — or an already-lowered
    `analysis.LoweredProgram`. The graph side runs the write-site
    taint analysis (KV-WRITE-NONCANONICAL, RNG-KEY-TAINT), the
    scatter-race prover (SCATTER-WRITE-OVERLAP) and the donation
    audit (DONATE-HOST-ALIAS). The host side always runs the
    thread-discipline lint (SERVE-UNLOCKED-SHARED, SERVE-LOCK-ORDER)
    over serving/ + io/ (or `thread_paths`). With `target=None` only
    the host-side lint runs. Returns
    ``{"findings": [Finding...], "graph": {...}, "threads": {...}}``;
    the same data the CLI's ``--determinism`` flag prints and
    determinism_manifests/*.json pins per serving config (the
    `lint_determinism` gate)."""
    from .analysis.determinism import analyze_determinism
    from .analysis.lowering import LoweredProgram
    from .analysis.threads import lint_thread_discipline

    findings, graph = [], {}
    program = None
    if target is not None:
        if isinstance(target, LoweredProgram):
            program = target
        elif hasattr(target, "analysis_program"):
            program = target.analysis_program(**program_kw)
        else:
            raise TypeError(
                "determinism_report wants a serving decoder (an object "
                "with .analysis_program) or a LoweredProgram, got "
                f"{type(target).__name__}")
        res = analyze_determinism(program)
        findings += res.findings
        graph = res.metrics
    tfound, threads = lint_thread_discipline(paths=thread_paths)
    findings += tfound
    if print_report:
        if graph:
            print(f"== determinism: {program.name} ==")
            print(f"  pool writes {graph['n_canonical_writes']}/"
                  f"{graph['n_pool_writes']} canonical over "
                  f"{graph['n_pool_buffers']} buffer(s); "
                  f"{graph['n_rng_sites']} RNG site(s); overlap pairs "
                  f"{graph['n_proven_disjoint']}/"
                  f"{graph['n_overlap_pairs']} proven disjoint; "
                  f"{graph['n_alias_outputs']} alias output(s) of "
                  f"{graph['n_donated_args']} donated arg(s)")
        print(f"== threads: {threads['n_threaded_classes']}/"
              f"{threads['n_classes']} classes threaded, "
              f"{threads['n_shared_paths']} unlocked shared path(s) ==")
        if findings:
            for f in findings:
                print(f"  {f}")
        else:
            print("  clean (0 findings)")
    return {"findings": findings, "graph": graph, "threads": threads}


def input_pipeline_stats():
    """Aggregate telemetry of every live `io.DeviceLoader`/prefetcher:
    batches prefetched, current/max queue depth, host time blocked
    waiting on input, H2D enqueue time. The observability half of the
    async input pipeline — when `time_blocked_on_input_s` grows with
    step count, the pipeline (not the chip) is the bottleneck: raise
    `depth`, add DataLoader workers, or cheapen the transform."""
    from .io.prefetch import prefetch_stats
    return prefetch_stats()


def diagnose(model_or_fn, *example_inputs, context=None, print_report=True):
    """Graph Doctor house call: lower `model_or_fn` on CPU, run the full
    paddle_tpu.analysis pass catalog (layout, dtype, host-transfer,
    graph-shape, collective, dy2static AST lint), and return the Report.
    The numerics checkers above catch *runtime* failures; this catches
    the *structural* ones (activation transposes, f32 upcasts, host
    callbacks) before a chip ever sees the program."""
    from .analysis import analyze, analyze_layer
    from .nn.layer_base import Layer
    args = [x._value if isinstance(x, Tensor) else x
            for x in example_inputs]
    if isinstance(model_or_fn, Layer):
        report = analyze_layer(model_or_fn, *args, context=context)
    else:
        report = analyze(model_or_fn, *args, context=context)
    if print_report:
        print(report)
    return report

_state = {"enabled": False}


def enable_check_nan_inf(enable=True):
    _state["enabled"] = bool(enable)


def check_nan_inf_enabled():
    return _state["enabled"]


class TensorCheckerConfig:  # reference paddle.amp.debugging API parity
    def __init__(self, enable=True, debug_mode=None, **kw):
        self.enable = enable

    def __enter__(self):
        self._prev = _state["enabled"]
        _state["enabled"] = self.enable
        return self

    def __exit__(self, *exc):
        _state["enabled"] = self._prev
        return False


def check_numerics(x, name="tensor"):
    """Returns x unchanged; poisons it to NaN-free guarantee by erroring the
    step if non-finite values appear. Works inside jit via jnp.where +
    debug check: non-finite → replaced with inf-signal that callers assert on
    host; eagerly raises immediately."""
    def _f(v):
        finite = jnp.all(jnp.isfinite(v.astype(jnp.float32)))
        # keep a data dependency so XLA can't DCE the check
        return jax.lax.cond(finite, lambda t: t,
                            lambda t: t * jnp.float32(jnp.nan).astype(t.dtype), v)
    if isinstance(x, Tensor):
        out = apply_op(_f, x)
        # host-side readback only outside tracing (tracers poison via the
        # lax.cond above instead)
        if isinstance(out._value, jax.Array) and \
                not isinstance(out._value, jax.core.Tracer):
            import numpy as np
            if not np.isfinite(np.asarray(out._value.astype(jnp.float32))).all():
                raise FloatingPointError(f"non-finite values detected in {name}")
        return out
    return _f(x)


def assert_finite_pytree(tree, name="pytree"):
    """Host-side assertion over a pytree of concrete arrays (post-step)."""
    import numpy as np
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf._value if isinstance(leaf, Tensor) else leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(f"non-finite values in {name}: {bad[:8]}")
