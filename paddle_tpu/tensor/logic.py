"""Comparison / logical ops — API of reference python/paddle/tensor/logic.py."""
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = [
    "equal", "equal_all", "not_equal", "greater_than", "greater_equal",
    "less_than", "less_equal", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "is_empty", "allclose", "isclose", "is_tensor",
]


def _cmp(fn):
    def op(x, y, name=None):
        return apply_op(fn, x, y)
    return op


equal = _cmp(lambda a, b: a == b)
not_equal = _cmp(lambda a, b: a != b)
greater_than = _cmp(lambda a, b: a > b)
greater_equal = _cmp(lambda a, b: a >= b)
less_than = _cmp(lambda a, b: a < b)
less_equal = _cmp(lambda a, b: a <= b)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)


def logical_not(x, name=None):
    return apply_op(jnp.logical_not, x)


def bitwise_not(x, name=None):
    return apply_op(jnp.bitwise_not, x)


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def is_tensor(x):
    return isinstance(x, Tensor)
