"""Comparison / logical ops — API of reference python/paddle/tensor/logic.py."""
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = [
    "equal", "equal_all", "not_equal", "greater_than", "greater_equal",
    "less_than", "less_equal", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "is_empty", "allclose", "isclose", "is_tensor",
]


def _cmp(fn, opname):
    # reference comparison signature is (x, y, name=None) — no `out`;
    # only the logical_*/bitwise_* families take one (see _logical)
    def op(x, y, name=None):
        return apply_op(fn, x, y)
    op.__name__ = opname
    return op


def _logical(fn, opname):
    # `out` is accepted for signature parity but IGNORED, exactly like
    # the reference's dygraph _logical_op: eager mode always returns a
    # fresh tensor and leaves `out` untouched
    def op(x, y, out=None, name=None):
        return apply_op(fn, x, y)
    op.__name__ = opname
    return op


equal = _cmp(lambda a, b: a == b, "equal")
not_equal = _cmp(lambda a, b: a != b, "not_equal")
greater_than = _cmp(lambda a, b: a > b, "greater_than")
greater_equal = _cmp(lambda a, b: a >= b, "greater_equal")
less_than = _cmp(lambda a, b: a < b, "less_than")
less_equal = _cmp(lambda a, b: a <= b, "less_equal")
logical_and = _logical(jnp.logical_and, "logical_and")
logical_or = _logical(jnp.logical_or, "logical_or")
logical_xor = _logical(jnp.logical_xor, "logical_xor")
bitwise_and = _logical(jnp.bitwise_and, "bitwise_and")
bitwise_or = _logical(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _logical(jnp.bitwise_xor, "bitwise_xor")


def _unary_out(fn, opname):
    # `out` accepted for parity, ignored in eager mode (see _cmp)
    def op(x, out=None, name=None):
        return apply_op(fn, x)
    op.__name__ = opname
    return op


logical_not = _unary_out(jnp.logical_not, "logical_not")
bitwise_not = _unary_out(jnp.bitwise_not, "bitwise_not")


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def is_tensor(x):
    return isinstance(x, Tensor)
