"""Search/sort ops — API of reference python/paddle/tensor/search.py."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import canonical
from ..framework.core import Tensor, apply_op

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "searchsorted", "kthvalue",
    "mode", "masked_fill", "index_fill", "bucketize",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(v):
        out = jnp.argmax(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis), keepdims=keepdim and axis is not None)
        return out.astype(canonical(dtype) if jax.config.jax_enable_x64 else jnp.int32)
    return apply_op(_f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(v):
        out = jnp.argmin(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis), keepdims=keepdim and axis is not None)
        return out.astype(canonical(dtype) if jax.config.jax_enable_x64 else jnp.int32)
    return apply_op(_f, x)


def argsort(x, axis=-1, descending=False, name=None):
    def _f(v):
        idx = jnp.argsort(v, axis=axis, descending=descending)
        return idx
    return apply_op(_f, x)


def sort(x, axis=-1, descending=False, name=None):
    return apply_op(lambda v: jnp.sort(v, axis=axis, descending=descending), x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k._value)

    def _f(v):
        ax = -1 if axis is None else int(axis)
        moved = jnp.moveaxis(v, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)
    vals, idx = apply_op(_f, x)
    return vals, idx


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def _f(seq, v):
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1]))
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int32)
    return apply_op(_f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _f(v):
        sorted_v = jnp.sort(v, axis=axis)
        idx_sorted = jnp.argsort(v, axis=axis)
        vals = jnp.take(sorted_v, k - 1, axis=axis)
        idx = jnp.take(idx_sorted, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    v, i = apply_op(_f, x)
    return v, i


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(x._value)
    moved = np.moveaxis(arr, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], arr.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for r in range(flat.shape[0]):
        uniq, counts = np.unique(flat[r], return_counts=True)
        best = uniq[np.argmax(counts[::-1])] if False else uniq[len(counts) - 1 - np.argmax(counts[::-1])]
        vals[r] = best
        idxs[r] = np.where(flat[r] == best)[0][-1]
    shp = moved.shape[:-1]
    v, i = vals.reshape(shp), idxs.reshape(shp)
    if keepdim:
        v, i = np.expand_dims(v, axis), np.expand_dims(i, axis)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(i))


def masked_fill(x, mask, value, name=None):
    v = value._value if isinstance(value, Tensor) else value
    return apply_op(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask)


def index_fill(x, index, axis, value, name=None):
    v = value._value if isinstance(value, Tensor) else value

    def _f(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[i].set(jnp.asarray(v, a.dtype))
        return jnp.moveaxis(moved, 0, axis)
    return apply_op(_f, x, index)
