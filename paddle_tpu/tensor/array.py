"""TensorArray ops — reference python/paddle/tensor/array.py. In dygraph the
array is a plain Python list (matches reference dygraph branch); static mode
uses the same list captured by the tracer."""
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["create_array", "array_read", "array_write", "array_length"]


def _idx(i):
    if isinstance(i, Tensor):
        return int(i.numpy().reshape(()))
    return int(i)


def create_array(dtype="float32", initialized_list=None):
    arr = []
    if initialized_list is not None:
        arr.extend(initialized_list)
    return arr


def array_write(x, i, array=None):
    i = _idx(i)
    if array is None:
        array = []
    while len(array) <= i:
        array.append(None)
    array[i] = x
    return array


def array_read(array, i):
    return array[_idx(i)]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int32))
