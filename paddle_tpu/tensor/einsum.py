"""Einsum — API of reference python/paddle/tensor/einsum.py; XLA lowers
contractions straight onto the MXU via dot_general."""
import jax.numpy as jnp

from ..framework.core import apply_op

__all__ = ["einsum"]


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op(lambda *vs: jnp.einsum(equation, *vs), *operands)
