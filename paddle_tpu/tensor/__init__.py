"""paddle_tpu.tensor — aggregates op modules and monkey-patches them as
Tensor methods (reference: python/paddle/tensor/__init__.py tensor_method_func
+ monkey_patch_varbase)."""
from ..framework import set_printoptions  # noqa: F401
import jax.numpy as _jnp

from ..framework.core import Tensor
from . import array, attribute, creation, einsum, linalg, logic, manipulation, math, random, search, stat
from .array import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .einsum import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

# names excluded from the BULK patch loop: Tensor properties that must
# not be shadowed (shape), creation/random free functions whose first
# argument is not a tensor, and names needing a hand-written method form
# — four of which (rank / is_empty / is_tensor / broadcast_shape) are
# patched explicitly at the end of _patch_tensor_methods.
_SKIP = {
    "shape", "rank", "to_tensor", "as_tensor", "is_tensor", "numel",
    "seed", "get_rng_state", "set_rng_state", "rand", "randn", "randint",
    "randperm", "meshgrid", "broadcast_shape", "is_empty",
}


def _patch_tensor_methods():
    for mod in (attribute, creation, einsum, linalg, logic, manipulation, math, random, search, stat):
        for name in getattr(mod, "__all__", []):
            if name in _SKIP:
                continue
            fn = getattr(mod, name)
            if callable(fn) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # extra aliases paddle exposes as methods
    Tensor.mean = stat.mean
    Tensor.var = stat.var
    Tensor.std = stat.std
    Tensor.add = math.add
    Tensor.add_ = math.add_
    Tensor.subtract = math.subtract
    Tensor.multiply = math.multiply
    Tensor.divide = math.divide
    Tensor.matmul = math.matmul
    # reference numel() returns a 0-D Tensor (int(t.numel()) and
    # arithmetic both work through the Tensor wrapper)
    Tensor.numel = lambda self: Tensor(_jnp.asarray(self.size))
    # last four names from the reference tensor_method_func list
    Tensor.rank = attribute.rank
    Tensor.is_empty = logic.is_empty
    Tensor.is_tensor = logic.is_tensor
    Tensor.broadcast_shape = \
        lambda self, y_shape: math.broadcast_shape(self.shape, y_shape)


_patch_tensor_methods()
