"""paddle_tpu.tensor — aggregates op modules and monkey-patches them as
Tensor methods (reference: python/paddle/tensor/__init__.py tensor_method_func
+ monkey_patch_varbase)."""
from ..framework import set_printoptions  # noqa: F401
from ..framework.core import Tensor
from . import array, attribute, creation, einsum, linalg, logic, manipulation, math, random, search, stat
from .array import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .einsum import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

# names that are Tensor properties or core methods — never overwrite
_SKIP = {
    "shape", "rank", "to_tensor", "as_tensor", "is_tensor", "numel",
    "seed", "get_rng_state", "set_rng_state", "rand", "randn", "randint",
    "randperm", "meshgrid", "broadcast_shape", "is_empty",
}


def _patch_tensor_methods():
    for mod in (attribute, creation, einsum, linalg, logic, manipulation, math, random, search, stat):
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or hasattr(Tensor, name) and name not in getattr(mod, "__all__", []):
                continue
            fn = getattr(mod, name)
            if callable(fn) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # extra aliases paddle exposes as methods
    Tensor.mean = stat.mean
    Tensor.var = stat.var
    Tensor.std = stat.std
    Tensor.add = math.add
    Tensor.add_ = math.add_
    Tensor.subtract = math.subtract
    Tensor.multiply = math.multiply
    Tensor.divide = math.divide
    Tensor.matmul = math.matmul
    Tensor.numel = lambda self: self.size


_patch_tensor_methods()
