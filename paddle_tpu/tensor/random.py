"""Random ops — API of reference python/paddle/tensor/random.py.
Eager calls draw deterministic keys from the global seeded stream
(framework/random.py); inside jit users should pass explicit keys via
paddle_tpu.framework.random or use the functional model APIs."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _rng
from ..framework.core import Tensor, apply_op
from ..framework.dtype import canonical, dtype as _dt, get_default_dtype

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "uniform_", "normal", "standard_normal", "poisson", "bernoulli",
    "multinomial", "exponential_", "seed", "get_rng_state", "set_rng_state",
]

seed = _rng.seed
get_rng_state = _rng.get_rng_state
set_rng_state = _rng.set_rng_state


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None):
    d = canonical(dtype) if dtype else _dt(get_default_dtype())
    return Tensor(jax.random.uniform(_rng.next_key(), _shape(shape), d))


def randn(shape, dtype=None, name=None):
    d = canonical(dtype) if dtype else _dt(get_default_dtype())
    return Tensor(jax.random.normal(_rng.next_key(), _shape(shape), d))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_rng.next_key(), _shape(shape), low, high).astype(canonical(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = canonical(dtype) if dtype else x.dtype
    return Tensor(jax.random.randint(_rng.next_key(), tuple(x.shape), low, high).astype(d))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_rng.next_key(), int(n)).astype(canonical(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = canonical(dtype) if dtype else _dt(get_default_dtype())
    return Tensor(jax.random.uniform(_rng.next_key(), _shape(shape), d, min, max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = _rng.next_key()
    return x._inplace_update(lambda v: jax.random.uniform(key, v.shape, v.dtype, min, max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        d = jnp.result_type(m) if hasattr(m, "dtype") else _dt(get_default_dtype())
        return Tensor(jax.random.normal(_rng.next_key(), shp, d) * s + m)
    d = _dt(get_default_dtype())
    return Tensor(jax.random.normal(_rng.next_key(), _shape(shape or (1,)), d) * std + mean)


def poisson(x, name=None):
    key = _rng.next_key()
    return apply_op(lambda v: jax.random.poisson(key, v, v.shape).astype(v.dtype), x)


def bernoulli(x, name=None):
    key = _rng.next_key()
    return apply_op(lambda v: jax.random.bernoulli(key, v, v.shape).astype(v.dtype), x)


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _rng.next_key()

    def _f(v):
        logits = jnp.log(jnp.maximum(v, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=(num_samples,) + v.shape[:-1]).T \
                if v.ndim > 1 else jax.random.categorical(key, logits, shape=(num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    out = apply_op(_f, x)
    return out.astype(canonical("int64"))


def exponential_(x, lam=1.0, name=None):
    key = _rng.next_key()
    return x._inplace_update(lambda v: jax.random.exponential(key, v.shape, v.dtype) / lam)


def check_shape(shape):
    """Validate a shape argument (reference python/paddle/tensor/random.py
    check_shape): entries must be positive ints (or -1 placeholders)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int, np.integer)) and not hasattr(s, "_value"):
                raise TypeError(f"shape entries must be int, got {type(s)}")
    return shape


__all__ += ["check_shape"]
