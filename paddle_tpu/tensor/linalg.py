"""Linear algebra — API of reference python/paddle/tensor/linalg.py.
Decompositions route through jax.numpy.linalg / lax.linalg (XLA custom calls
on TPU; QR/SVD/Cholesky run on device, eig falls back to host like the
reference's LAPACK path for CPU-only ops)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = [
    "norm", "dist", "cond", "det", "slogdet", "inv", "pinv", "solve",
    "cholesky", "cholesky_solve", "triangular_solve", "lstsq", "qr", "svd",
    "matrix_power", "matrix_rank", "eig", "eigh", "eigvals", "eigvalsh",
    "lu", "multi_dot", "cross", "t", "histogram", "bincount", "corrcoef",
    "cov",
]


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if isinstance(p, str) and p != "fro":
        # reference tensor/linalg.py:282 — "Not supported: ord < 0 and
        # nuclear norm" (paddle.linalg.cond DOES take p='nuc')
        raise ValueError(
            f"norm does not support string order {p!r}; supported: 'fro', "
            "0, 1, 2, inf, -inf and positive real p (use linalg.cond for "
            "p='nuc')")

    def _f(v):
        if axis is None:
            flat = v.reshape(-1)
            if p in ("fro", 2):
                return jnp.sqrt(jnp.sum(flat.astype(jnp.float32) ** 2)).astype(v.dtype)
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            if p == np.inf:
                return jnp.max(jnp.abs(flat))
            if p == -np.inf:
                return jnp.min(jnp.abs(flat))
            if p == 0:
                return jnp.sum((flat != 0).astype(v.dtype))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p == np.inf:
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op(_f, x)


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p) if p not in ("fro",) else p)


def cond(x, p=None, name=None):
    return apply_op(lambda v: jnp.linalg.cond(v, p=p), x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def slogdet(x, name=None):
    def _f(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])
    return apply_op(_f, x)


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def cholesky(x, upper=False, name=None):
    def _f(v):
        c = jnp.linalg.cholesky(v)
        return jnp.swapaxes(c, -1, -2) if upper else c
    return apply_op(_f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def _f(b, chol):
        c = jnp.swapaxes(chol, -1, -2) if upper else chol
        z = jax.scipy.linalg.solve_triangular(c, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(c, -1, -2), z, lower=False)
    return apply_op(_f, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return apply_op(
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular),
        x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return tuple(apply_op(_f, x, y))


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return apply_op(lambda v: jnp.linalg.qr(v, mode="r"), x)
    outs = apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x)
    return tuple(outs)


def svd(x, full_matrices=False, name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), x)
    return tuple(outs)


def matrix_power(x, n, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol), x)


def eig(x, name=None):
    # general eig is CPU-only in XLA (like reference's LAPACK-on-host)
    arr = np.asarray(x._value)
    w, v = np.linalg.eig(arr)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    arr = np.asarray(x._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(arr)))


def eigh(x, UPLO="L", name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x)
    return tuple(outs)


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def _f(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    lu_t, piv_t = apply_op(_f, x)
    if get_infos:
        return lu_t, piv_t, Tensor(jnp.zeros((), jnp.int32))
    return lu_t, piv_t


def multi_dot(x, name=None):
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs), *x)


def cross(x, y, axis=9, name=None):
    def _f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(_f, x, y)


def t(input, name=None):
    if input.ndim < 2:
        return apply_op(lambda v: v, input)
    return apply_op(lambda v: jnp.swapaxes(v, -1, -2), input)


def histogram(input, bins=100, min=0, max=0, name=None):
    def _f(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h.astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    return apply_op(_f, input)


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return apply_op(lambda v, w: jnp.bincount(v, w, minlength=minlength,
                                                  length=max(minlength, int(np.asarray(x._value).max()) + 1)),
                        x, weights)
    n = max(minlength, int(np.asarray(x._value).max()) + 1 if x.size else minlength)
    return apply_op(lambda v: jnp.bincount(v, minlength=minlength, length=n), x)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), x)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack an LU factorization — reference
    python/paddle/tensor/linalg.py:lu_unpack (pivots are 1-based as from lu())."""
    def _f(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        if unpack_ludata:
            l = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
            u = jnp.triu(lu_[..., :k, :])
        else:
            l = jnp.zeros(lu_.shape[:-2] + (m, k), lu_.dtype)
            u = jnp.zeros(lu_.shape[:-2] + (k, n), lu_.dtype)
        if unpack_pivots:
            # pivots (1-based row swaps) -> permutation matrix P with A = P L U
            def perm_from_piv(pv):
                perm = jnp.arange(m)
                def body(i, perm):
                    j = pv[i] - 1
                    pi, pj = perm[i], perm[j]
                    return perm.at[i].set(pj).at[j].set(pi)
                return jax.lax.fori_loop(0, pv.shape[0], body, perm)
            flat_piv = piv.reshape((-1, piv.shape[-1]))
            perms = jax.vmap(perm_from_piv)(flat_piv)
            p = jax.nn.one_hot(perms, m, dtype=lu_.dtype)          # rows of P^T
            p = jnp.swapaxes(p, -1, -2)
            p = p.reshape(lu_.shape[:-2] + (m, m))
        else:
            p = jnp.zeros(lu_.shape[:-2] + (m, m), lu_.dtype)
        return p, l, u
    return apply_op(_f, x, y)


__all__ += ["lu_unpack"]
