"""Math ops — API of reference python/paddle/tensor/math.py + ops.py,
lowered to jnp/lax so XLA fuses elementwise chains into MXU-adjacent kernels.

Also installs arithmetic operators on Tensor (reference does this in
python/paddle/fluid/dygraph/math_op_patch.py via monkey_patch_math_varbase).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import canonical
from ..framework.core import Tensor, apply_op

__all__ = []  # populated at bottom


def _un(name, fn):
    """Register a unary elementwise op + its inplace alias."""
    def op(x, name=None):
        return apply_op(fn, x)
    op.__name__ = name
    globals()[name] = op
    __all__.append(name)

    def op_(x, name=None):
        return x._inplace_update(fn)
    op_.__name__ = name + "_"
    globals()[name + "_"] = op_
    __all__.append(name + "_")
    return op


def _bin(name, fn):
    def op(x, y, name=None):
        return apply_op(fn, x, y)
    op.__name__ = name
    globals()[name] = op
    __all__.append(name)

    def op_(x, y, name=None):
        yv = y._value if isinstance(y, Tensor) else y
        return x._inplace_update(lambda v: fn(v, yv))
    op_.__name__ = name + "_"
    globals()[name + "_"] = op_
    __all__.append(name + "_")
    return op


# -- elementwise unary ------------------------------------------------------
for _n, _f in {
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt, "abs": jnp.abs, "ceil": jnp.ceil,
    "floor": jnp.floor, "round": jnp.round,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
    "acos": jnp.arccos, "atan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh, "atanh": jnp.arctanh, "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv, "sign": jnp.sign, "neg": jnp.negative,
    "reciprocal": jnp.reciprocal, "square": jnp.square,
    "sigmoid": jax.nn.sigmoid, "angle": jnp.angle, "conj": jnp.conj,
    "real": jnp.real, "imag": jnp.imag, "frac": lambda v: v - jnp.trunc(v),
    "digamma": jax.scipy.special.digamma, "lgamma": jax.scipy.special.gammaln,
    "i0": jax.scipy.special.i0, "i1": jax.scipy.special.i1,
    "deg2rad": jnp.deg2rad, "rad2deg": jnp.rad2deg,
    "nan_to_num": jnp.nan_to_num,
}.items():
    _un(_n, _f)

# -- elementwise binary -----------------------------------------------------
for _n, _f in {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "hypot": jnp.hypot,
    "copysign": jnp.copysign, "nextafter": jnp.nextafter,
    "heaviside": jnp.heaviside, "gcd": jnp.gcd, "lcm": jnp.lcm,
    "ldexp": jnp.ldexp, "inner": jnp.inner, "outer": jnp.outer,
    "kron": jnp.kron,
}.items():
    _bin(_n, _f)


def logit(x, eps=None, name=None):
    """logit(x) = log(x / (1-x)); with eps, x is clamped to
    [eps, 1-eps] first (reference tensor/math.py logit)."""
    def _f(v, _e=eps):
        if _e is not None:
            v = jnp.clip(v, _e, 1.0 - _e)
        return jax.scipy.special.logit(v)
    return apply_op(_f, x)


def trunc(input, name=None):
    return apply_op(jnp.trunc, input)


def trunc_(input, name=None):
    return input._inplace_update(jnp.trunc)


def logit_(x, eps=None, name=None):
    def _f(v, _e=eps):
        if _e is not None:
            v = jnp.clip(v, _e, 1.0 - _e)
        return jax.scipy.special.logit(v)
    return x._inplace_update(_f)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _f(v, _s=scale, _b=bias):
        _s = _s._value if isinstance(_s, Tensor) else _s
        out = v * jnp.asarray(_s, v.dtype) + jnp.asarray(_b, v.dtype) if bias_after_scale \
            else (v + jnp.asarray(_b, v.dtype)) * jnp.asarray(_s, v.dtype)
        return out
    return apply_op(_f, x)


def clip(x, min=None, max=None, name=None):
    mn = min._value if isinstance(min, Tensor) else min
    mx = max._value if isinstance(max, Tensor) else max
    return apply_op(lambda v: jnp.clip(v, mn, mx), x)


def clip_(x, min=None, max=None, name=None):
    mn = min._value if isinstance(min, Tensor) else min
    mx = max._value if isinstance(max, Tensor) else max
    return x._inplace_update(lambda v: jnp.clip(v, mn, mx))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply_op(lambda a, b: a + weight * (b - a), x, y)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), x)


def multiplex(inputs, index, name=None):
    def _f(idx, *vs):
        stacked = jnp.stack(vs, axis=0)  # [n, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]
    return apply_op(_f, index, *inputs)


# -- reductions -------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._value)
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    def _f(v):
        d = canonical(dtype) if dtype is not None else (
            jnp.int64 if v.dtype in (jnp.bool_, jnp.int32) and jax.config.jax_enable_x64 else None)
        return jnp.sum(v, axis=ax, dtype=d, keepdims=keepdim)
    return apply_op(_f, x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.mean(v, axis=ax, keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.max(v, axis=ax, keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.min(v, axis=ax, keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.prod(v, axis=ax, keepdims=keepdim,
                                       dtype=canonical(dtype) if dtype else None), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jax.scipy.special.logsumexp(v, axis=ax, keepdims=keepdim), x)


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.all(v, axis=ax, keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.any(v, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.nansum(v, axis=ax, keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op(lambda v: jnp.nanmean(v, axis=ax, keepdims=keepdim), x)


# -- cumulative -------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    def _f(v):
        vv = v.reshape(-1) if axis is None else v
        return jnp.cumsum(vv, axis=0 if axis is None else int(axis))
    return apply_op(_f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    def _f(v):
        vv = v.reshape(-1) if dim is None else v
        return jnp.cumprod(vv, axis=0 if dim is None else int(dim))
    return apply_op(_f, x)


def cummax(x, axis=None, dtype="int64", name=None):
    def _f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.cummax(vv, axis=ax)
        # indices: position of the running max
        idx = jnp.arange(vv.shape[ax]).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        idx = jnp.broadcast_to(idx, vv.shape)
        inds = jax.lax.cummax(jnp.where(vv == vals, idx, 0), axis=ax)
        return vals, inds.astype(canonical(dtype))
    return apply_op(_f, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def _f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.cummin(vv, axis=ax)
        idx = jnp.arange(vv.shape[ax]).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        idx = jnp.broadcast_to(idx, vv.shape)
        inds = jax.lax.cummax(jnp.where(vv == vals, idx, 0), axis=ax)
        return vals, inds.astype(canonical(dtype))
    return apply_op(_f, x)


def logcumsumexp(x, axis=None, name=None):
    def _f(v):
        vv = v.reshape(-1) if axis is None else v
        return jax.lax.cumlogsumexp(vv, axis=0 if axis is None else int(axis))
    return apply_op(_f, x)


# -- matmul family (MXU path) ----------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(_f, x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def matmul_v2(x, y, trans_x=False, trans_y=False):  # legacy fluid op name
    return matmul(x, y, trans_x, trans_y)


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    kw = {}
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)
    def _f(v, *rest):
        pre = rest[0] if prepend is not None else None
        app = rest[-1] if append is not None else None
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)
    return apply_op(_f, *args, **kw)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def increment(x, value=1.0, name=None):
    return x._inplace_update(lambda v: v + jnp.asarray(value, v.dtype))


def isnan(x, name=None):
    return apply_op(jnp.isnan, x)


def isinf(x, name=None):
    return apply_op(jnp.isinf, x)


def isfinite(x, name=None):
    return apply_op(jnp.isfinite, x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def _f(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out
    return apply_op(_f, *inputs)


def take(x, index, mode="raise", name=None):
    return apply_op(lambda v, i: jnp.take(v.reshape(-1), i, mode="clip" if mode != "wrap" else "wrap"), x, index)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


__all__ += [
    "scale", "clip", "clip_", "lerp", "stanh", "multiplex", "sum", "mean",
    "max", "min", "amax", "amin", "prod", "logsumexp", "all", "any",
    "count_nonzero", "nansum", "nanmean", "cumsum", "cumprod", "cummax",
    "cummin", "logcumsumexp", "matmul", "mm", "bmm", "dot", "addmm", "mv",
    "diff", "trace", "increment", "isnan", "isinf", "isfinite",
    "broadcast_shape", "add_n", "take", "rot90",
]


# -- operator monkey-patch on Tensor ---------------------------------------
def _patch_operators():
    import operator as _op  # noqa: F401

    def _binop(fn, reverse=False):
        def method(self, other):
            if reverse:
                return apply_op(lambda b, a: fn(a, b), self, other) if isinstance(other, Tensor) \
                    else apply_op(lambda a: fn(other, a), self)
            return apply_op(fn, self, other)
        return method

    T = Tensor
    T.__add__ = _binop(jnp.add)
    T.__radd__ = _binop(jnp.add, True)
    T.__sub__ = _binop(jnp.subtract)
    T.__rsub__ = _binop(jnp.subtract, True)
    T.__mul__ = _binop(jnp.multiply)
    T.__rmul__ = _binop(jnp.multiply, True)
    T.__truediv__ = _binop(jnp.divide)
    T.__rtruediv__ = _binop(jnp.divide, True)
    T.__floordiv__ = _binop(jnp.floor_divide)
    T.__rfloordiv__ = _binop(jnp.floor_divide, True)
    T.__mod__ = _binop(jnp.mod)
    T.__rmod__ = _binop(jnp.mod, True)
    T.__pow__ = _binop(jnp.power)
    T.__rpow__ = _binop(jnp.power, True)
    T.__matmul__ = _binop(jnp.matmul)
    T.__rmatmul__ = _binop(jnp.matmul, True)
    T.__neg__ = lambda self: apply_op(jnp.negative, self)
    T.__abs__ = lambda self: apply_op(jnp.abs, self)
    T.__invert__ = lambda self: apply_op(jnp.logical_not, self)
    T.__eq__ = _binop(lambda a, b: a == b)
    T.__ne__ = _binop(lambda a, b: a != b)
    T.__lt__ = _binop(lambda a, b: a < b)
    T.__le__ = _binop(lambda a, b: a <= b)
    T.__gt__ = _binop(lambda a, b: a > b)
    T.__ge__ = _binop(lambda a, b: a >= b)
    T.__and__ = _binop(jnp.logical_and)
    T.__or__ = _binop(jnp.logical_or)
    T.__xor__ = _binop(jnp.logical_xor)
    T.__hash__ = object.__hash__


_patch_operators()


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """reference python/paddle/tensor/math.py:2750 diagonal()."""
    return apply_op(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


def renorm(x, p, axis, max_norm):
    """Clamp the p-norm of every sub-tensor along `axis` to max_norm
    (reference python/paddle/tensor/math.py:1649)."""
    def _renorm(v):
        dims = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return v * factor
    return apply_op(_renorm, x)


__all__ += ["diagonal", "renorm"]


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """Inplace scale — reference python/paddle/tensor/math.py:scale_."""
    if bias_after_scale:
        return x._inplace_update(lambda v: v * jnp.asarray(scale, v.dtype)
                                 + jnp.asarray(bias, v.dtype))
    return x._inplace_update(lambda v: (v + jnp.asarray(bias, v.dtype))
                             * jnp.asarray(scale, v.dtype))


def lerp_(x, y, weight, name=None):
    """Inplace lerp — reference python/paddle/tensor/math.py:lerp_."""
    yv = y._value if hasattr(y, "_value") else y
    wv = weight._value if hasattr(weight, "_value") else weight
    return x._inplace_update(lambda v: v + jnp.asarray(wv, v.dtype)
                             * (jnp.asarray(yv, v.dtype) - v))


def inverse(x, name=None):
    """Matrix inverse — reference python/paddle/tensor/math.py:inverse."""
    return apply_op(jnp.linalg.inv, x)


__all__ += ["scale_", "lerp_", "inverse", "logit", "logit_", "trunc", "trunc_"]
