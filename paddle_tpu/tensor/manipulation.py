"""Shape/layout manipulation — API of reference python/paddle/tensor/manipulation.py."""
import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "stack", "unstack",
    "split", "vsplit", "hsplit", "dsplit", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "flatten", "flip", "roll", "chunk",
    "unbind", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd", "scatter_nd_add", "index_select", "index_sample",
    "masked_select", "take_along_axis", "put_along_axis", "where",
    "tensordot", "moveaxis", "swapaxes", "repeat_interleave", "flatten_",
    "as_real", "as_complex", "unique", "unique_consecutive", "strided_slice",
    "slice", "crop", "fill_", "zero_", "shard_index", "rotate_half",
]


def _ival(v):
    return int(v._value) if isinstance(v, Tensor) else int(v)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._value)]
    else:
        shape = [_ival(s) for s in shape]
    return apply_op(lambda v: jnp.reshape(v, shape), x)


def reshape_(x, shape, name=None):
    shape = [_ival(s) for s in shape]
    return x._inplace_update(lambda v: jnp.reshape(v, shape))


def transpose(x, perm=None, name=None):
    return apply_op(lambda v: jnp.transpose(v, None if perm is None else tuple(perm)), x)


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, axis0, axis1), x)


def concat(x, axis=0, name=None):
    axis = _ival(axis)
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=axis), *x)


def stack(x, axis=0, name=None):
    return apply_op(lambda *vs: jnp.stack(vs, axis=axis), *x)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    outs = apply_op(lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)), x)
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    axis = _ival(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [s if not isinstance(s, Tensor) else int(s._value) for s in num_or_sections]
        n_unknown = builtins_sum(1 for s in sizes if s in (-1, None))
        if n_unknown:
            known = builtins_sum(s for s in sizes if s not in (-1, None))
            sizes = [(dim - known) if s in (-1, None) else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def _f(v):
        return tuple(jax.lax.slice_in_dim(v, o, o + s, axis=axis) for o, s in zip(offsets, sizes))
    return list(apply_op(_f, x))


def builtins_sum(it, start=0):
    total = start
    for v in it:
        total = total + v
    return total


def vsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=1)


def dsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(input, axis=0):
    return unstack(input, axis)


def squeeze(x, axis=None, name=None):
    ax = None
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a for a in (int(a) for a in axes) if x.shape[a] == 1)
    return apply_op(lambda v: jnp.squeeze(v, axis=ax), x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._value, x._producer = out._value, out._producer
    return x


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(int(a._value) if isinstance(a, Tensor) else int(a) for a in axes)
    return apply_op(lambda v: jnp.expand_dims(v, axes), x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._value, x._producer = out._value, out._producer
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def _f(v):
        shp = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, shp)
    return apply_op(_f, x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._value, x._producer = out._value, out._producer
    return x


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op(lambda v: jnp.flip(v, axis=ax), x)


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda v: jnp.roll(v, shifts, axis=axis), x)


def tile(x, repeat_times, name=None):
    reps = tuple(_ival(r) for r in repeat_times) if isinstance(repeat_times, (list, tuple)) \
        else (_ival(repeat_times),)
    return apply_op(lambda v: jnp.tile(v, reps), x)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._value)]
    shape = [_ival(s) for s in shape]

    def _f(v):
        tgt = list(shape)
        off = len(tgt) - v.ndim
        for i in range(v.ndim):
            if tgt[off + i] == -1:
                tgt[off + i] = v.shape[i]
        return jnp.broadcast_to(v, tgt)
    return apply_op(_f, x)


def expand_as(x, y, name=None):
    return apply_op(lambda v, t: jnp.broadcast_to(v, t.shape), x, y)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(input, name=None):
    outs = apply_op(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *input)
    return list(outs)


def gather(x, index, axis=0, name=None):
    axis = _ival(axis)
    return apply_op(lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=axis), x, index)


def gather_nd(x, index, name=None):
    def _f(v, idx):
        k = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return v[flat_idx]
    return apply_op(_f, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def _f(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        # paddle overwrite=False: zero target rows then accumulate
        zeroed = v.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply_op(_f, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._value, x._producer = out._value, out._producer
    return x


def scatter_nd(index, updates, shape, name=None):
    def _f(i, u):
        out = jnp.zeros(tuple(shape), u.dtype)
        return out.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op(_f, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    return apply_op(lambda v, i, u: v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u), x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda v, i: jnp.take(v, i, axis=axis), x, index)


def index_sample(x, index):
    return apply_op(lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index)


def masked_select(x, mask, name=None):
    # data-dependent output shape: eager-only (like reference dygraph op)
    return Tensor(np.asarray(x._value)[np.asarray(mask._value)])


def take_along_axis(arr, indices, axis, broadcast=True):
    return apply_op(lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    def _f(v, i, u):
        u = jnp.broadcast_to(jnp.asarray(u, v.dtype), i.shape)
        dims = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(i.ndim)])
                for d, s in enumerate(i.shape)]
        full_idx = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape)
                         for d in range(i.ndim))
        if reduce == "add":
            return v.at[full_idx].add(u)
        if reduce in ("mul", "multiply"):
            return v.at[full_idx].multiply(u)
        return v.at[full_idx].set(u)
    return apply_op(_f, arr, indices, values)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    arr = np.asarray(x._value)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)[:, None]) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = np.asarray(axes._value).tolist()
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return apply_op(lambda v, r: jnp.repeat(v, r, axis=axis,
                                                total_repeat_length=int(np.asarray(repeats._value).sum())),
                        x, repeats)
    return apply_op(lambda v: jnp.repeat(v, repeats, axis=axis), x)


def as_complex(x, name=None):
    return apply_op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(x._value)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(x._value)
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0], dtype=bool)
    keep[1:] = np.any(arr[1:] != arr[:-1], axis=tuple(range(1, arr.ndim))) if arr.ndim > 1 \
        else arr[1:] != arr[:-1]
    out = [Tensor(jnp.asarray(arr[keep]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.flatnonzero(keep)
        out.append(Tensor(jnp.asarray(np.diff(np.append(idx, arr.shape[0])))))
    return out[0] if len(out) == 1 else tuple(out)


def slice(input, axes, starts, ends):
    def _f(v):
        out = v
        for ax, s, e in zip(axes, starts, ends):
            s = _ival(s); e = _ival(e)
            e = builtins_min(e, out.shape[ax])
            out = jax.lax.slice_in_dim(out, s, e, axis=ax)
        return out
    return apply_op(_f, input)


def builtins_min(a, b):
    return a if a < b else b


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _f(v):
        idx = [builtins_slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins_slice(_ival(s), _ival(e), _ival(st))
        return v[tuple(idx)]
    return apply_op(_f, x)


builtins_slice = builtins.slice


def crop(x, shape=None, offsets=None, name=None):
    offs = [0] * x.ndim if offsets is None else [_ival(o) for o in offsets]
    shp = x.shape if shape is None else [x.shape[i] if _ival(s) == -1 else _ival(s)
                                         for i, s in enumerate(shape)]
    return apply_op(lambda v: jax.lax.dynamic_slice(v, offs, shp), x)


def fill_(x, value):
    return x._inplace_update(lambda v: jnp.full_like(v, value))


def zero_(x):
    return x._inplace_update(lambda v: jnp.zeros_like(v))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards

    def _f(v):
        in_shard = (v // size) == shard_id
        return jnp.where(in_shard, v % size, ignore_value)
    return apply_op(_f, input)


def rotate_half(x):  # helper used by rotary embeddings
    return apply_op(lambda v: jnp.concatenate([-v[..., v.shape[-1] // 2:], v[..., : v.shape[-1] // 2]], axis=-1), x)


def cast(x, dtype):
    """paddle.cast — reference python/paddle/tensor/manipulation.py cast()."""
    return x.astype(dtype)


def reverse(x, axis, name=None):
    """Alias of flip (legacy name, reference fluid.layers.reverse)."""
    return flip(x, axis)


def tolist(x):
    """Function form of Tensor.tolist (reference tensor/manipulation.py)."""
    return x.tolist()


__all__ += ["cast", "reverse", "tolist", "nonzero"]


def put_along_axis_(arr, indices, values, axis, reduce="assign"):
    """Inplace put_along_axis — reference
    python/paddle/tensor/manipulation.py:put_along_axis_."""
    iv = indices._value if hasattr(indices, "_value") else indices
    uv = values._value if hasattr(values, "_value") else values

    def _f(v):
        u = jnp.broadcast_to(jnp.asarray(uv, v.dtype), iv.shape)
        dims = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(iv.ndim)])
                for d, s in enumerate(iv.shape)]
        full_idx = tuple(iv if d == axis else jnp.broadcast_to(dims[d], iv.shape)
                         for d in range(iv.ndim))
        if reduce == "add":
            return v.at[full_idx].add(u)
        if reduce in ("mul", "multiply"):
            return v.at[full_idx].multiply(u)
        return v.at[full_idx].set(u)
    return arr._inplace_update(_f)


__all__ += ["put_along_axis_"]
