"""Tensor creation ops — API of reference python/paddle/tensor/creation.py,
implemented on jnp (XLA-eager on TPU, constant-folded under jit)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _rng
from ..framework.core import Tensor, apply_op, to_tensor  # noqa: F401
from ..framework.dtype import dtype as _dt, get_default_dtype

__all__ = [
    "to_tensor", "zeros", "zeros_like", "ones", "ones_like", "full",
    "full_like", "arange", "linspace", "logspace", "eye", "empty",
    "empty_like", "meshgrid", "diag", "diagflat", "tril", "triu",
    "assign", "clone", "complex", "as_tensor",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def _fdt(dtype):
    from ..framework.dtype import canonical
    return canonical(dtype) if dtype is not None else _dt(get_default_dtype())


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _fdt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _fdt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = "bool" if isinstance(fill_value, bool) else (
            "int64" if isinstance(fill_value, int) else get_default_dtype())
    from ..framework.dtype import canonical
    return Tensor(jnp.full(_shape(shape), fill_value, canonical(dtype)))


def zeros_like(x, dtype=None, name=None):
    return apply_op(lambda v: jnp.zeros_like(v, dtype=_dt(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply_op(lambda v: jnp.ones_like(v, dtype=_dt(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op(lambda v: jnp.full_like(v, fill_value, dtype=_dt(dtype)), x)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _item(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _item(start), _item(end), _item(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(isinstance(v, (int, np.integer)) for v in (start, end, step)) \
            else get_default_dtype()
    from ..framework.dtype import canonical
    return Tensor(jnp.arange(start, end, step, dtype=canonical(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = int(num.item() if isinstance(num, Tensor) else num)
    return Tensor(jnp.linspace(start, stop, num, dtype=_fdt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base), dtype=_fdt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=_fdt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)  # deterministic "empty" (XLA buffers are managed)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply_op(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *args)
    return list(outs)


def diag(x, offset=0, padding_value=0, name=None):
    def _f(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, v.dtype)
            return out + jnp.diag(v, k=offset) - jnp.diag(jnp.full(v.shape, padding_value, v.dtype), k=offset)
        return jnp.diag(v, k=offset)
    return apply_op(_f, x)


def diagflat(x, offset=0, name=None):
    return apply_op(lambda v: jnp.diagflat(v, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.triu(v, k=diagonal), x)


def assign(x, output=None):
    src = to_tensor(x) if not isinstance(x, Tensor) else x
    out = apply_op(lambda v: v + 0 if v.dtype != jnp.bool_ else v, src)
    if output is not None:
        output._value = out._value
        return output
    return out


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply_op(lambda r, i: jax.lax.complex(r, i), real, imag)


def as_tensor(data, dtype=None):
    return to_tensor(data, dtype=dtype)
