"""Statistics ops — API of reference python/paddle/tensor/stat.py."""
import jax.numpy as jnp

from ..framework.core import apply_op

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile", "nanquantile", "numel"]


def _axis(axis):
    if axis is None or isinstance(axis, int):
        return axis
    return tuple(int(a) for a in axis)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.median(v, axis=_axis(axis), keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim), x)


def numel(x, name=None):
    from .creation import to_tensor
    return to_tensor(x.size, dtype="int64")
