"""Tensor attribute helpers — API of reference python/paddle/tensor/attribute.py."""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.dtype import is_complex_dtype, is_floating_point_dtype, is_integer_dtype

__all__ = ["shape", "rank", "is_floating_point", "is_integer", "is_complex"]


def shape(input):
    return Tensor(jnp.asarray(np.array(input.shape, dtype=np.int32)))


def rank(input):
    return Tensor(jnp.asarray(input.ndim))


def is_floating_point(x):
    return is_floating_point_dtype(x.dtype)


def is_integer(x):
    return is_integer_dtype(x.dtype)


def is_complex(x):
    return is_complex_dtype(x.dtype)

