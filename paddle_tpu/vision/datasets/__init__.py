"""Vision datasets — API of reference python/paddle/vision/datasets.
Zero-egress environment: downloads are unavailable; datasets load from a
user-provided local path, plus synthetic generators for testing/benching."""
import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "FakeImageDataset", "Flowers", "VOC2012",
           "flowers", "voc2012"]


class FakeImageDataset(Dataset):
    """Synthetic images+labels (deterministic) — benchmarking / CI stand-in."""

    def __init__(self, num_samples=1000, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype("float32")
        label = rng.randint(0, self.num_classes)
        if self.transform:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """Loads the classic idx-format files from `image_path`/`label_path`."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise NotImplementedError(
                "zero-egress environment: pass local image_path/label_path")
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:  # fall back to deterministic synthetic digits
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1000 if mode == "train" else 200
            self.images = (rng.rand(n, 28, 28) * 255).astype("uint8")
            self.labels = rng.randint(0, 10, n).astype("int64")

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise NotImplementedError(
                "zero-egress environment: pass a local data_file")
        self.transform = transform
        if data_file and os.path.exists(data_file):
            raw = np.load(data_file, allow_pickle=True)
            self.images, self.labels = raw["images"], raw["labels"]
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1000 if mode == "train" else 200
            self.images = (rng.rand(n, 3, 32, 32) * 255).astype("uint8")
            self.labels = rng.randint(0, self._n_classes, n).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    _n_classes = 10


class Cifar100(_CifarBase):
    _n_classes = 100


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")


def _load_image(path):
    if path.endswith(".npy"):
        return np.load(path)
    if path.lower().endswith((".jpg", ".jpeg")):
        # native libjpeg decode (runtime/cxx/image_ops.cpp) — measurably
        # faster than PIL per image; falls through on any failure
        from ...runtime import image as _rimage
        if _rimage.native_available():
            try:
                with open(path, "rb") as f:
                    img = _rimage.decode_jpeg(f.read())
                if img.shape[-1] == 1:
                    # match the PIL branch's convert("RGB") for grayscale
                    img = np.repeat(img, 3, axis=-1)
                return img
            except Exception:
                pass
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise RuntimeError("PIL unavailable; use .npy images") from e


class DatasetFolder(Dataset):
    """class-per-subdir layout (reference DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=_IMG_EXTS, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _load_image
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.classes = classes
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat folder of images, no labels."""

    def __init__(self, root, loader=None, extensions=_IMG_EXTS, transform=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(extensions))]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference vision/datasets/flowers.py). This
    environment has no network, so the archive paths are REQUIRED:
    data_file (102flowers.tgz), label_file (imagelabels.mat), setid_file
    (setid.mat). Streaming: images are read from the tar on demand."""

    _MODE_FLAG = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        mode = mode.lower()
        assert mode in self._MODE_FLAG, mode
        if not (data_file and label_file and setid_file):
            raise ValueError(
                "zero-egress environment: pass local data_file "
                "(102flowers.tgz), label_file (imagelabels.mat) and "
                "setid_file (setid.mat) — downloads are disabled")
        import tarfile

        import scipy.io as scio
        self.transform = transform
        # extract ONCE (as the reference does): random access into a gzip
        # tar re-decompresses from the start per read, and an open tar
        # handle can't be shared across forked DataLoader workers
        self.data_path = data_file + ".extracted/"
        if not os.path.isdir(os.path.join(self.data_path, "jpg")):
            # extract to a temp sibling and rename atomically so a crashed
            # or concurrent extraction never masquerades as a complete one
            tmp = data_file + f".extracting.{os.getpid()}/"
            with tarfile.open(data_file) as t:
                t.extractall(tmp, filter="data")
            import shutil
            target = self.data_path.rstrip("/")
            try:
                os.rename(tmp, target)
            except OSError:
                if os.path.isdir(os.path.join(target, "jpg")):
                    # a concurrent worker finished first
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    # stale partial dir from an interrupted extraction:
                    # replace it with the fresh complete one (another
                    # worker may be doing the same — whoever loses the
                    # rename race defers to the winner's install)
                    shutil.rmtree(target, ignore_errors=True)
                    try:
                        os.rename(tmp, target)
                    except OSError:
                        if os.path.isdir(os.path.join(target, "jpg")):
                            shutil.rmtree(tmp, ignore_errors=True)
                        else:
                            raise
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self._MODE_FLAG[mode]][0]

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]], "int64")
        image = _load_image(
            os.path.join(self.data_path, "jpg/image_%05d.jpg" % index))
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (reference vision/datasets/voc2012.py).
    Requires the local VOCtrainval tar (zero-egress); yields
    (image, segmentation-label) arrays streamed from the archive."""

    _SETS = {"train": "train.txt", "valid": "val.txt",
             "trainval": "trainval.txt"}
    _VOC = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        assert mode in self._SETS, mode
        if not data_file:
            raise ValueError(
                "zero-egress environment: pass the local "
                "VOCtrainval_11-May-2012.tar as data_file — downloads "
                "are disabled")
        self.transform = transform
        self._data_file = data_file
        self._tars = {}          # pid -> handle (fork-safe: one per worker)
        tar = self._get_tar()
        listing = f"{self._VOC}/ImageSets/Segmentation/{self._SETS[mode]}"
        names = tar.extractfile(listing).read().decode().split()
        self.data = [f"{self._VOC}/JPEGImages/{n}.jpg" for n in names]
        self.labels = [f"{self._VOC}/SegmentationClass/{n}.png"
                       for n in names]

    def _get_tar(self):
        """A forked DataLoader worker must not share the parent's tar
        file descriptor (concurrent seeks corrupt reads): one handle per
        process."""
        import tarfile
        pid = os.getpid()
        if pid not in self._tars:
            self._tars.clear()
            self._tars[pid] = tarfile.open(self._data_file)
        return self._tars[pid]

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_tars"] = {}
        return state

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        tar = self._get_tar()
        img = np.asarray(Image.open(_io.BytesIO(
            tar.extractfile(self.data[idx]).read())).convert("RGB"))
        lab = np.asarray(Image.open(_io.BytesIO(
            tar.extractfile(self.labels[idx]).read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return len(self.data)


def flowers(*a, **k):
    """Legacy reader-style entry (reference paddle.dataset.flowers)."""
    return Flowers(*a, **k)


def voc2012(*a, **k):
    return VOC2012(*a, **k)
