"""paddle_tpu.vision — reference python/paddle/vision/__init__.py."""
from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401

__all__ = ["models", "transforms", "ops", "datasets",
           "get_image_backend", "set_image_backend", "image_load"]
