"""Vision ops — reference python/paddle/vision/ops.py (roi_align, nms, box ops)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box", "DeformConv2D",
           "distribute_fpn_proposals", "generate_proposals", "PSRoIPool", "RoIAlign", "RoIPool"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Greedy NMS (host-side; data-dependent output like reference CPU kernel)."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores) \
        if scores is not None else np.ones(len(b), np.float32)
    order = np.argsort(-s)
    if category_idxs is not None:
        cats = np.asarray(category_idxs._value if isinstance(category_idxs, Tensor)
                          else category_idxs)
    else:
        cats = np.zeros(len(b), np.int64)
    keep = []
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.asarray(keep[:top_k] if top_k else keep, np.int64)
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _f(feat, rois, _nums):
        n_rois = rois.shape[0]
        c = feat.shape[1]
        # map rois to batch indices
        counts = _nums
        batch_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                               total_repeat_length=n_rois)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        # sample grid centers
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (rh[:, None] / oh)  # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (rw[:, None] / ow)  # [R, ow]

        # vectorized bilinear gather: for each roi r, grid point (i,j)
        def per_roi(bi, yy, xx):
            fb = feat[bi]  # [C,H,W]
            h, w = fb.shape[-2:]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = (yy - y0)[:, None]
            wx = (xx - x0)[None, :]
            g = lambda yi, xi: fb[:, yi[:, None], xi[None, :]]
            out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1_) * (1 - wy) * wx
                   + g(y1_, x0) * wy * (1 - wx) + g(y1_, x1_) * wy * wx)
            return out  # [C, oh, ow]
        return jax.vmap(per_roi)(batch_idx, ys, xs)
    return apply_op(_f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale, aligned=False)


class _RoIBase:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale


class RoIAlign(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class RoIPool(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class PSRoIPool(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        raise NotImplementedError("position-sensitive RoI pool: planned with detection suite")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def _f(prior, var, target):
        pw = prior[:, 2] - prior[:, 0] + (0 if box_normalized else 1)
        ph = prior[:, 3] - prior[:, 1] + (0 if box_normalized else 1)
        px = prior[:, 0] + pw * 0.5
        py = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + (0 if box_normalized else 1)
            th = target[:, 3] - target[:, 1] + (0 if box_normalized else 1)
            tx = target[:, 0] + tw * 0.5
            ty = target[:, 1] + th * 0.5
            ox = (tx - px) / pw / var[:, 0]
            oy = (ty - py) / ph / var[:, 1]
            ow = jnp.log(tw / pw) / var[:, 2]
            oh = jnp.log(th / ph) / var[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        # decode
        ox = var[:, 0] * target[:, 0] * pw + px
        oy = var[:, 1] * target[:, 1] * ph + py
        ow = jnp.exp(var[:, 2] * target[:, 2]) * pw
        oh = jnp.exp(var[:, 3] * target[:, 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], axis=1)
    return apply_op(_f, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    raise NotImplementedError("yolo_box decode lands with the detection suite")


def distribute_fpn_proposals(*args, **kwargs):
    raise NotImplementedError("FPN ops land with the detection suite")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError("RPN ops land with the detection suite")


class DeformConv2D:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError("deformable conv: planned Pallas kernel")
