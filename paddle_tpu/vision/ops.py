"""Vision ops — reference python/paddle/vision/ops.py (roi_align, nms, box ops)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box", "yolo_loss",
           "deform_conv2d", "DeformConv2D", "psroi_pool", "read_file", "decode_jpeg",
           "distribute_fpn_proposals", "generate_proposals", "PSRoIPool", "RoIAlign",
           "RoIPool", "ConvNormActivation"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Greedy NMS (host-side; data-dependent output like reference CPU kernel)."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores) \
        if scores is not None else np.ones(len(b), np.float32)
    order = np.argsort(-s)
    if category_idxs is not None:
        cats = np.asarray(category_idxs._value if isinstance(category_idxs, Tensor)
                          else category_idxs)
    else:
        cats = np.zeros(len(b), np.int64)
    keep = []
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.asarray(keep[:top_k] if top_k else keep, np.int64)
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _f(feat, rois, _nums):
        n_rois = rois.shape[0]
        c = feat.shape[1]
        # map rois to batch indices
        counts = _nums
        batch_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                               total_repeat_length=n_rois)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        # sample grid centers
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (rh[:, None] / oh)  # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (rw[:, None] / ow)  # [R, ow]

        # vectorized bilinear gather: for each roi r, grid point (i,j)
        def per_roi(bi, yy, xx):
            fb = feat[bi]  # [C,H,W]
            h, w = fb.shape[-2:]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = (yy - y0)[:, None]
            wx = (xx - x0)[None, :]
            g = lambda yi, xi: fb[:, yi[:, None], xi[None, :]]
            out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1_) * (1 - wy) * wx
                   + g(y1_, x0) * wy * (1 - wx) + g(y1_, x1_) * wy * wx)
            return out  # [C, oh, ow]
        return jax.vmap(per_roi)(batch_idx, ys, xs)
    return apply_op(_f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale, aligned=False)


class _RoIBase:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale


class RoIAlign(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class RoIPool(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class PSRoIPool(_RoIBase):
    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def _f(prior, var, target):
        pw = prior[:, 2] - prior[:, 0] + (0 if box_normalized else 1)
        ph = prior[:, 3] - prior[:, 1] + (0 if box_normalized else 1)
        px = prior[:, 0] + pw * 0.5
        py = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + (0 if box_normalized else 1)
            th = target[:, 3] - target[:, 1] + (0 if box_normalized else 1)
            tx = target[:, 0] + tw * 0.5
            ty = target[:, 1] + th * 0.5
            ox = (tx - px) / pw / var[:, 0]
            oy = (ty - py) / ph / var[:, 1]
            ow = jnp.log(tw / pw) / var[:, 2]
            oh = jnp.log(th / ph) / var[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        # decode
        ox = var[:, 0] * target[:, 0] * pw + px
        oy = var[:, 1] * target[:, 1] * ph + py
        ow = jnp.exp(var[:, 2] * target[:, 2]) * pw
        oh = jnp.exp(var[:, 3] * target[:, 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], axis=1)
    return apply_op(_f, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLOv3 box decode — reference python/paddle/vision/ops.py:yolo_box +
    phi yolo_box kernel."""
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = an.shape[0]

    def _f(v, imsz):
        n, c, h, w = v.shape
        v = v.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bx = (jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
              + gx[None, None, None, :]) / w
        by = (jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
              + gy[None, None, :, None]) / h
        bw = jnp.exp(v[:, :, 2]) * an[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * an[None, :, 1, None, None] / (h * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4])
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
        keep = conf.reshape(n, -1) >= conf_thresh
        boxes = boxes * keep[..., None]
        scores = scores * keep[..., None]
        return boxes, scores
    return apply_op(_f, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss — reference python/paddle/vision/ops.py:yolo_loss
    + fluid yolov3_loss op (coordinate BCE/L1, objectness with ignore mask,
    per-class BCE)."""
    all_an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_idx = np.asarray(anchor_mask, np.int32)
    an = all_an[mask_idx]                  # anchors used at this scale
    na = an.shape[0]

    def _bce(logit, target):
        return jax.nn.softplus(logit) - logit * target

    def _f(v, gbox, glabel, gscore):
        n, c, h, w = v.shape
        v = v.reshape(n, na, 5 + class_num, h, w)
        px, py = v[:, :, 0], v[:, :, 1]
        pw, ph = v[:, :, 2], v[:, :, 3]
        pconf, pcls = v[:, :, 4], v[:, :, 5:]
        nb = gbox.shape[1]
        gx, gy = gbox[..., 0], gbox[..., 1]              # (N, B) normalized
        gw, gh = gbox[..., 2], gbox[..., 3]
        valid = (gw > 0) & (gh > 0)
        # best anchor over ALL anchors by centered shape-IoU
        gw_pix = gw * w * downsample_ratio
        gh_pix = gh * h * downsample_ratio
        inter = jnp.minimum(gw_pix[..., None], all_an[None, None, :, 0]) *             jnp.minimum(gh_pix[..., None], all_an[None, None, :, 1])
        union = gw_pix[..., None] * gh_pix[..., None]             + all_an[None, None, :, 0] * all_an[None, None, :, 1] - inter
        best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # (N, B)
        # position in this scale's anchor list (or -1)
        in_mask = (best_anchor[..., None] == mask_idx[None, None, :])
        a_idx = jnp.argmax(in_mask, axis=-1)
        responsible = valid & in_mask.any(axis=-1)
        gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
        tx = gx * w - gi
        ty = gy * h - gj
        # jnp.take: a_idx may be a tracer (jitted training step) and numpy
        # fancy-indexing would force a concrete conversion
        an_w = jnp.take(jnp.asarray(an[:, 0]), a_idx)
        an_h = jnp.take(jnp.asarray(an[:, 1]), a_idx)
        tw = jnp.log(jnp.maximum(gw_pix, 1e-9) / jnp.maximum(an_w, 1e-9))
        th = jnp.log(jnp.maximum(gh_pix, 1e-9) / jnp.maximum(an_h, 1e-9))
        box_scale = 2.0 - gw * gh
        score_w = gscore if gscore is not None else jnp.ones_like(gx)
        bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, nb))
        sel = (bidx, a_idx, gj, gi)                       # (N, B) gather indices
        wpos = (responsible * box_scale * score_w)
        loc = _bce(px[sel], tx) + _bce(py[sel], ty)             + jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th)
        loss_loc = jnp.sum(loc * wpos, axis=1)
        # objectness: positives at responsible cells; negatives elsewhere
        # unless their best pred-gt IoU exceeds ignore_thresh
        obj_target = jnp.zeros((n, na, h, w))
        obj_weight = jnp.ones((n, na, h, w))
        obj_target = obj_target.at[sel].max(responsible.astype(jnp.float32))
        pos_w = jnp.where(responsible, score_w, 0.0)
        obj_pos_w = jnp.ones((n, na, h, w)).at[sel].max(pos_w)
        # predicted boxes for ignore mask
        cx = (jax.nn.sigmoid(px) + jnp.arange(w, dtype=jnp.float32)[None, None, None, :]) / w
        cy = (jax.nn.sigmoid(py) + jnp.arange(h, dtype=jnp.float32)[None, None, :, None]) / h
        bw = jnp.exp(jnp.clip(pw, -10, 10)) * an[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(jnp.clip(ph, -10, 10)) * an[None, :, 1, None, None] / (h * downsample_ratio)
        p1x, p1y = cx - bw / 2, cy - bh / 2
        p2x, p2y = cx + bw / 2, cy + bh / 2
        g1x, g1y = gx - gw / 2, gy - gh / 2
        g2x, g2y = gx + gw / 2, gy + gh / 2
        def iou_with_gt(b):
            ix = jnp.maximum(0.0, jnp.minimum(p2x, g2x[:, b, None, None, None])
                             - jnp.maximum(p1x, g1x[:, b, None, None, None]))
            iy = jnp.maximum(0.0, jnp.minimum(p2y, g2y[:, b, None, None, None])
                             - jnp.maximum(p1y, g1y[:, b, None, None, None]))
            inter = ix * iy
            uni = bw * bh + (gw * gh)[:, b, None, None, None] - inter
            return jnp.where(valid[:, b, None, None, None],
                             inter / jnp.maximum(uni, 1e-9), 0.0)
        best_iou = jnp.max(jnp.stack([iou_with_gt(b) for b in range(nb)]), axis=0)
        ignore = (best_iou > ignore_thresh) & (obj_target < 0.5)
        obj_weight = jnp.where(ignore, 0.0, obj_weight) * jnp.where(
            obj_target > 0.5, obj_pos_w, 1.0)
        loss_obj = jnp.sum(_bce(pconf, obj_target) * obj_weight, axis=(1, 2, 3))
        # classification at responsible cells (label smooth as in the
        # yolov3_loss kernel: positive -> 1 - 1/C, negative -> 1/C)
        onehot = jax.nn.one_hot(glabel.astype(jnp.int32), class_num)
        if use_label_smooth and class_num > 1:
            delta = 1.0 / class_num
            tcls = onehot * (1.0 - 2.0 * delta) + delta
        else:
            tcls = onehot
        pcls_sel = jnp.moveaxis(pcls, 2, -1)[sel]         # (N, B, class_num)
        loss_cls = jnp.sum(jnp.sum(_bce(pcls_sel, tcls), axis=-1) * wpos, axis=1)
        return loss_loc + loss_obj + loss_cls
    return apply_op(_f, x, gt_box, gt_label, gt_score)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling — reference python/paddle/vision/ops.py:
    psroi_pool (bin (i, j) of output channel c averages input channel
    c*ph*pw + i*pw + j over the bin's spatial region)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph_, pw_ = output_size

    def _f(v, bx):
        n, c, h, w = v.shape
        oc = c // (ph_ * pw_)
        rois = bx * spatial_scale                        # (R, 4) x1 y1 x2 y2
        # reference repeats image features per roi according to boxes_num;
        # here boxes are all against image 0 unless boxes_num maps them
        counts = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                            else boxes_num)
        img_of_roi = np.repeat(np.arange(len(counts)), counts)
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def one_roi(roi, img):
            x1, y1, x2, y2 = roi
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_h = rh / ph_
            bin_w = rw / pw_
            feat = v[img]                                # (C, H, W)
            outs = []
            for i in range(ph_):
                row = []
                for j in range(pw_):
                    hs = y1 + i * bin_h
                    he = y1 + (i + 1) * bin_h
                    ws_ = x1 + j * bin_w
                    we = x1 + (j + 1) * bin_w
                    mask_y = (ys >= jnp.floor(hs)) & (ys < jnp.ceil(he))
                    mask_x = (xs >= jnp.floor(ws_)) & (xs < jnp.ceil(we))
                    m = (mask_y[:, None] & mask_x[None, :]).astype(v.dtype)
                    cnt = jnp.maximum(m.sum(), 1.0)
                    chans = feat.reshape(oc, ph_ * pw_, h, w)[:, i * pw_ + j]
                    row.append(jnp.sum(chans * m[None], axis=(1, 2)) / cnt)
                outs.append(jnp.stack(row, axis=-1))      # (oc, pw)
            return jnp.stack(outs, axis=-2)               # (oc, ph, pw)
        return jnp.stack([one_roi(rois[r], int(img_of_roi[r]))
                          for r in range(rois.shape[0])])
    return apply_op(_f, x, boxes)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable convolution v1/v2 — reference python/paddle/vision/ops.py:
    deform_conv2d. Bilinear-samples input at offset positions per kernel tap,
    then contracts with the weight (one big einsum -> MXU-friendly)."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def _f(v, off, wgt, b, msk):
        n, cin, h, w = v.shape
        cout, cin_g, kh, kw = wgt.shape
        ho = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        wo = (w + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        k = kh * kw
        off = off.reshape(n, deformable_groups, k, 2, ho, wo)
        base_y = (jnp.arange(ho) * st[0] - pd[0]).astype(jnp.float32)
        base_x = (jnp.arange(wo) * st[1] - pd[1]).astype(jnp.float32)
        ky = (jnp.arange(kh) * dl[0]).astype(jnp.float32)
        kx = (jnp.arange(kw) * dl[1]).astype(jnp.float32)
        kyx = jnp.stack(jnp.meshgrid(ky, kx, indexing="ij"), -1).reshape(k, 2)
        # sample positions: (N, dg, k, ho, wo)
        py = base_y[None, None, None, :, None] + kyx[None, None, :, 0, None, None]             + off[:, :, :, 0]
        px = base_x[None, None, None, None, :] + kyx[None, None, :, 1, None, None]             + off[:, :, :, 1]
        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        dy = py - y0
        dx = px - x0

        def gather(img, iy, ix):
            """img (N, dg, cpg, H, W); iy/ix (N, dg, k, ho, wo) int."""
            valid = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            flat = img.reshape(n, deformable_groups, -1, h * w)
            cpg = flat.shape[2]
            idx = (iyc * w + ixc).reshape(n, deformable_groups, 1, -1)
            idx = jnp.broadcast_to(idx, (n, deformable_groups, cpg, idx.shape[-1]))
            got = jnp.take_along_axis(flat, idx, axis=-1)
            got = got.reshape(n, deformable_groups, cpg, k, ho, wo)
            return got * valid[:, :, None].astype(img.dtype)
        imgg = v.reshape(n, deformable_groups, cin // deformable_groups, h, w)
        p00 = gather(imgg, y0, x0)
        p01 = gather(imgg, y0, x0 + 1)
        p10 = gather(imgg, y0 + 1, x0)
        p11 = gather(imgg, y0 + 1, x0 + 1)
        wy = dy[:, :, None]
        wx = dx[:, :, None]
        samp = (p00 * (1 - wy) * (1 - wx) + p01 * (1 - wy) * wx
                + p10 * wy * (1 - wx) + p11 * wy * wx)     # (N, dg, cpg, k, ho, wo)
        if msk is not None:
            samp = samp * msk.reshape(n, deformable_groups, 1, k, ho, wo)
        samp = samp.reshape(n, cin, k, ho, wo)
        wflat = wgt.reshape(groups, cout // groups, cin_g, k)
        sg = samp.reshape(n, groups, cin // groups, k, ho, wo)
        out = jnp.einsum("gock,ngckhw->ngohw", wflat, sg, optimize=True)
        out = out.reshape(n, cout, ho, wo)
        if b is not None:
            out = out + b[None, :, None, None]
        return out
    return apply_op(_f, x, offset, weight, bias, mask)


class DeformConv2D:
    """Layer wrapper over deform_conv2d — reference vision/ops.py:DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from ..nn.layer_base import Layer  # reuse parameter machinery
        from ..framework.core import Parameter
        from ..framework.random import next_key
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        fan_in = in_channels * ks[0] * ks[1] // groups
        bound = float(1.0 / np.sqrt(fan_in))
        self.weight = Parameter(jax.random.uniform(
            next_key(), (out_channels, in_channels // groups, ks[0], ks[1]),
            jnp.float32, -bound, bound))
        self.bias = None if bias_attr is False else Parameter(
            jax.random.uniform(next_key(), (out_channels,), jnp.float32, -bound, bound))

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None else [])

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation, self.deformable_groups,
                             self.groups, mask)


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor — reference vision/ops.py:read_file."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 — reference
    vision/ops.py:decode_jpeg (host-side via PIL; data loading is host work)."""
    import io
    from PIL import Image
    raw = bytes(np.asarray(x._value if isinstance(x, Tensor) else x, np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale — reference
    python/paddle/vision/ops.py:distribute_fpn_proposals + phi
    distribute_fpn_proposals kernel.

    target_level = clip(floor(refer_level + log2(sqrt(area)/refer_scale)),
    min_level, max_level). Proposal routing is a host-side postprocessing
    stage (variable-size outputs), so this runs in numpy: returns
    (multi_rois [per level], restore_ind[, rois_num_per_level]).

    rois_num: per-image roi counts ([B] array/Tensor, or True for a
    single-image batch); when given, each level's rois stay grouped
    image-major and rois_num_per_level entries are [B] counts, matching
    the reference's batched contract.
    """
    rois = np.asarray(fpn_rois.numpy() if hasattr(fpn_rois, "numpy")
                      else fpn_rois, np.float32)
    if rois_num is None or rois_num is True:
        per_image = np.asarray([len(rois)], np.int64)
    else:
        per_image = np.asarray(
            rois_num.numpy() if hasattr(rois_num, "numpy") else rois_num,
            np.int64).reshape(-1)
    img_of = np.repeat(np.arange(len(per_image)), per_image)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-12))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, per_level_counts, order = [], [], []
    for L in range(min_level, max_level + 1):
        # image-major within each level so per-image counts slice cleanly
        idx = np.nonzero(lvl == L)[0]
        idx = idx[np.argsort(img_of[idx], kind="stable")]
        order.append(idx)
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        per_level_counts.append(np.bincount(
            img_of[idx], minlength=len(per_image)).astype(np.int32))
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    # restore_ind[i] = position of original roi i in the concatenated
    # per-level output (reference RestoreIndex semantics)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    restore_ind = Tensor(jnp.asarray(restore.reshape(-1, 1).astype(np.int32)))
    if rois_num is not None:
        return multi_rois, restore_ind, [
            Tensor(jnp.asarray(c)) for c in per_level_counts]
    return multi_rois, restore_ind


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation — reference
    python/paddle/vision/ops.py:generate_proposals + phi
    generate_proposals_v2 kernel.

    Per image: decode anchor deltas, clip to the image, drop boxes smaller
    than min_size, keep pre_nms_top_n by score, NMS, keep post_nms_top_n.
    Returns (rpn_rois, rpn_roi_probs[, rpn_rois_num]) like the reference.
    The variable-length NMS/stacking stage is host-side like the
    reference's CPU kernel.
    """
    def to_np(x):
        return np.asarray(x.numpy() if hasattr(x, "numpy") else x, np.float32)

    sc = to_np(scores)                       # [N, A, H, W]
    dl = to_np(bbox_deltas)                  # [N, 4A, H, W]
    im = to_np(img_size)                     # [N, 2] (h, w)
    an = to_np(anchors).reshape(-1, 4)       # [H*W*A, 4]
    var = to_np(variances).reshape(-1, 4)
    N, A = sc.shape[0], sc.shape[1]
    off = 1.0 if pixel_offset else 0.0

    # [N, A, H, W] -> [N, H*W*A]; deltas -> [N, H*W*A, 4] (phi layout)
    sc = sc.transpose(0, 2, 3, 1).reshape(N, -1)
    dl = dl.reshape(N, A, 4, dl.shape[2], dl.shape[3]) \
        .transpose(0, 3, 4, 1, 2).reshape(N, -1, 4)

    aw = an[:, 2] - an[:, 0] + off
    ah = an[:, 3] - an[:, 1] + off
    acx = an[:, 0] + aw * 0.5
    acy = an[:, 1] + ah * 0.5

    max_delta = float(np.log(1000.0 / 16.0))   # phi kernel's bbox clip
    min_size = max(float(min_size), 1.0)       # phi floors min_size to 1
    all_rois, all_probs, all_num = [], [], []
    for i in range(N):
        dx, dy, dw, dh = (dl[i, :, 0] * var[:, 0], dl[i, :, 1] * var[:, 1],
                          dl[i, :, 2] * var[:, 2], dl[i, :, 3] * var[:, 3])
        cx = dx * aw + acx
        cy = dy * ah + acy
        w = np.exp(np.minimum(dw, max_delta)) * aw
        h = np.exp(np.minimum(dh, max_delta)) * ah
        boxes = np.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], axis=1)
        H, W = im[i]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, W - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, H - off)
        keep_w = boxes[:, 2] - boxes[:, 0] + off
        keep_h = boxes[:, 3] - boxes[:, 1] + off
        valid = (keep_w >= min_size) & (keep_h >= min_size)
        idx = np.nonzero(valid)[0]
        s = sc[i, idx]
        if pre_nms_top_n > 0 and len(idx) > pre_nms_top_n:
            top = np.argsort(-s)[:pre_nms_top_n]
            idx, s = idx[top], s[top]
        b = boxes[idx]
        keep = nms(Tensor(jnp.asarray(b)), iou_threshold=nms_thresh,
                   scores=Tensor(jnp.asarray(s)))
        keep = np.asarray(keep.numpy() if hasattr(keep, "numpy") else keep)
        if post_nms_top_n > 0:
            keep = keep[:post_nms_top_n]
        all_rois.append(b[keep])
        all_probs.append(s[keep])
        all_num.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, axis=0)
                              if all_rois else np.zeros((0, 4), np.float32)))
    probs = Tensor(jnp.asarray(
        (np.concatenate(all_probs, axis=0) if all_probs
         else np.zeros((0,), np.float32)).reshape(-1, 1)))
    nums = Tensor(jnp.asarray(np.asarray(all_num, np.int32)))
    if return_rois_num:
        return rois, probs, nums
    return rois, probs


from ..nn import Sequential as _Sequential  # noqa: E402


class ConvNormActivation(_Sequential):
    """Conv2D + norm + activation block — reference
    python/paddle/vision/ops.py:ConvNormActivation. A Sequential subclass
    (like the reference) so isinstance checks and subclassing behave; TPU
    layout flows through Conv2D's data_format default."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=None,
                 activation_layer=None, dilation=1, bias=None):
        from .. import nn

        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        if activation_layer is None:
            activation_layer = nn.ReLU
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                            padding, dilation=dilation, groups=groups,
                            bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)
        self.out_channels = out_channels



