"""Image IO backend — reference python/paddle/vision/image.py:90-140
(get_image_backend / set_image_backend / image_load).

Backends: 'pil' (reference default; cv2 is not in this image) and 'native'
(paddle_tpu.runtime.image — off-GIL libjpeg decode, returns HWC uint8
ndarray; falls back to PIL for non-JPEG formats)."""
import os

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_backend = "pil"
_VALID = ("pil", "native", "cv2")


def set_image_backend(backend):
    global _backend
    if backend not in _VALID:
        raise ValueError(
            f"Expected backend in {_VALID}, got {backend!r}")
    if backend == "cv2":
        raise ImportError("cv2 is not available in this environment; use "
                          "'pil' or 'native'")
    _backend = backend


def get_image_backend():
    return _backend


def image_load(path, backend=None):
    """Load an image. 'pil' returns a PIL.Image (reference semantics);
    'native' returns an HWC uint8 ndarray decoded off the GIL."""
    backend = backend or _backend
    if backend not in _VALID:
        raise ValueError(f"Expected backend in {_VALID}, got {backend!r}")
    if backend == "cv2":
        raise ImportError("cv2 is not available in this environment; use "
                          "'pil' or 'native'")
    if backend == "native":
        from ..runtime.image import decode_jpeg
        ext = os.path.splitext(str(path))[1].lower()
        if ext in (".jpg", ".jpeg"):
            with open(path, "rb") as f:
                return decode_jpeg(f.read())
        # non-JPEG: PIL decode, same ndarray contract
        import numpy as np
        from PIL import Image
        arr = np.asarray(Image.open(path))
        return arr if arr.ndim == 3 else arr[:, :, None]
    from PIL import Image
    return Image.open(path)
