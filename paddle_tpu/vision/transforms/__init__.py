"""Vision transforms — reference python/paddle/vision/transforms (numpy/HWC
based host-side preprocessing, feeding the DataLoader pipeline)."""
import math
import numbers
import random

import numpy as np

from ...framework.core import Tensor

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "RandomRotation",
    "Pad", "Grayscale", "to_tensor", "resize", "normalize", "hflip", "vflip",
    "center_crop", "crop", "pad", "adjust_brightness", "adjust_contrast",
    "to_grayscale",
]


def _to_hwc_array(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def to_tensor(img, data_format="CHW"):
    arr = _to_hwc_array(img).astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _to_hwc_array(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if interpolation == "bilinear" and arr.dtype == np.uint8 and arr.ndim == 3:
        # hot path: native C++ bilinear (off-GIL), torch-compatible sampling
        from ...runtime.image import resize_bilinear
        return resize_bilinear(arr, (oh, ow)).astype(np.float32)
    import jax
    import jax.numpy as jnp
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[interpolation]
    out_shape = (oh, ow) + arr.shape[2:]
    return np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape, method=method))


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _to_hwc_array(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_hwc_array(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if self.padding:
            arr = pad(arr, self.padding)
        h, w = arr.shape[:2]
        th, tw = self.size
        top = random.randint(0, max(h - th, 0))
        left = random.randint(0, max(w - tw, 0))
        return crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return resize(crop(arr, top, left, ch, cw), self.size, self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size, self.interpolation)


def hflip(img):
    return _to_hwc_array(img)[:, ::-1]


def vflip(img):
    return _to_hwc_array(img)[::-1]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _to_hwc_array(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _to_hwc_array(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._value)
    else:
        arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = [mean] * 3 if isinstance(mean, numbers.Number) else mean
        self.std = [std] * 3 if isinstance(std, numbers.Number) else std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        return arr.transpose(self.order)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_hwc_array(img)
    if isinstance(padding, numbers.Number):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    l, t, r, b = padding
    widths = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(arr, widths, mode=mode, constant_values=fill)
    return np.pad(arr, widths, mode=mode)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


def adjust_brightness(img, brightness_factor):
    arr = _to_hwc_array(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi)


def adjust_contrast(img, contrast_factor):
    arr = _to_hwc_array(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    mean = arr.mean()
    return np.clip((arr - mean) * contrast_factor + mean, 0, hi)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_hwc_array(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_hwc_array(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        if self.value == 0:
            return arr
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = arr.mean(axis=-1, keepdims=True)
        hi = 255.0 if arr.max() > 1.5 else 1.0
        return np.clip(gray + (arr - gray) * f, 0, hi)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, numbers.Number):
            value = (-value, value)
        self.value = tuple(value)

    def _apply_image(self, img):
        if self.value == (0, 0):
            return _to_hwc_array(img)
        return adjust_hue(img, random.uniform(*self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness), ContrastTransform(contrast),
                           SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        angle = random.uniform(*self.degrees)
        arr = _to_hwc_array(img)
        return ndi.rotate(arr, angle, axes=(0, 1), reshape=False, order=1)


def to_grayscale(img, num_output_channels=1):
    arr = _to_hwc_array(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    return np.repeat(gray[..., None], num_output_channels, axis=-1)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)

# ---------------------------------------------------------------------------
# Geometric warps — reference python/paddle/vision/transforms/functional.py
# (affine/rotate/perspective/erase). Implemented as a single inverse
# homography warp with bilinear sampling in numpy (host-side preprocessing;
# device compute stays in the jitted training step).


def _warp(arr, inv_matrix, fill=0, interpolation="bilinear"):
    """Inverse-map warp: out[y, x] = in[H @ (x, y, 1)]. inv_matrix is 3x3."""
    arr = np.asarray(arr, dtype=np.float32)
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1).reshape(-1, 3).astype(np.float32)
    src = coords @ np.asarray(inv_matrix, dtype=np.float32).T
    denom = np.where(np.abs(src[:, 2:3]) < 1e-8, 1e-8, src[:, 2:3])
    sx, sy = src[:, 0] / denom[:, 0], src[:, 1] / denom[:, 0]
    if interpolation == "nearest":
        ix, iy = np.round(sx).astype(np.int64), np.round(sy).astype(np.int64)
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        out = np.full((h * w,) + arr.shape[2:], float(fill), dtype=np.float32)
        out[valid] = arr[iy[valid], ix[valid]]
        return out.reshape(arr.shape)
    x0, y0 = np.floor(sx).astype(np.int64), np.floor(sy).astype(np.int64)
    dx, dy = sx - x0, sy - y0
    out = np.zeros((h * w,) + arr.shape[2:], dtype=np.float32)
    wsum = np.zeros((h * w,), dtype=np.float32)
    for ox, oy, wt in ((0, 0, (1 - dx) * (1 - dy)), (1, 0, dx * (1 - dy)),
                       (0, 1, (1 - dx) * dy), (1, 1, dx * dy)):
        px, py = x0 + ox, y0 + oy
        valid = (px >= 0) & (px < w) & (py >= 0) & (py < h)
        wv = np.where(valid, wt, 0.0).astype(np.float32)
        pxc, pyc = np.clip(px, 0, w - 1), np.clip(py, 0, h - 1)
        sample = arr[pyc, pxc]
        out += (wv.reshape(-1, *([1] * (arr.ndim - 2)))) * sample
        wsum += wv
    out += np.where(wsum < 1e-6, float(fill), 0.0).reshape(-1, *([1] * (arr.ndim - 2)))
    return out.reshape(arr.shape)


def _affine_inv_matrix(angle, translate, scale, shear, center):
    cx, cy = center
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    # forward matrix M = T(translate) @ T(center) @ R(rot) @ Shear @ S(scale) @ T(-center)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = np.array([[scale * a, scale * b, 0.0],
                  [scale * c, scale * d, 0.0],
                  [0.0, 0.0, 1.0]], dtype=np.float64)
    m[0, 2] = translate[0] + cx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = translate[1] + cy - m[1, 0] * cx - m[1, 1] * cy
    return np.linalg.inv(m)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    arr = _to_hwc_array(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    inv = _affine_inv_matrix(angle, translate, scale, shear, center)
    return _warp(arr, inv, fill=fill, interpolation=interpolation)


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    arr = _to_hwc_array(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if expand:
        rot = math.radians(angle)
        nw = int(abs(w * math.cos(rot)) + abs(h * math.sin(rot)) + 0.5)
        nh = int(abs(h * math.cos(rot)) + abs(w * math.sin(rot)) + 0.5)
        pad_x, pad_y = (nw - w) // 2, (nh - h) // 2
        arr = np.pad(arr, [(pad_y, nh - h - pad_y), (pad_x, nw - w - pad_x)]
                     + [(0, 0)] * (arr.ndim - 2), constant_values=fill)
        center = ((nw - 1) * 0.5, (nh - 1) * 0.5)
    inv = _affine_inv_matrix(angle, (0, 0), 1.0, (0.0, 0.0), center)
    return _warp(arr, inv, fill=fill, interpolation=interpolation)


def _perspective_coeffs(startpoints, endpoints):
    """Solve the homography mapping endpoints -> startpoints (inverse warp)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    coeffs = np.linalg.solve(np.asarray(a, dtype=np.float64),
                             np.asarray(b, dtype=np.float64))
    return np.append(coeffs, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    arr = _to_hwc_array(img)
    inv = _perspective_coeffs(startpoints, endpoints)
    return _warp(arr, inv, fill=fill, interpolation=interpolation)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the rectangle [i:i+h, j:j+w] with value v. Accepts HWC/CHW arrays
    and paddle Tensors (reference erase works on CHW tensors)."""
    from ...tensor.creation import to_tensor as _tt
    if isinstance(img, Tensor):
        arr = np.array(img.numpy())
        if arr.ndim == 3:  # CHW
            arr[:, i:i + h, j:j + w] = np.broadcast_to(np.asarray(v, arr.dtype),
                                                       arr[:, i:i + h, j:j + w].shape)
        else:
            arr[..., i:i + h, j:j + w] = v
        return _tt(arr)
    arr = np.asarray(img) if inplace else np.array(img)
    arr[i:i + h, j:j + w] = v
    return arr


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via an RGB->HSV->RGB roundtrip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor is not in [-0.5, 0.5].")
    arr = _to_hwc_array(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    x = arr / hi
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc, minc = x.max(axis=-1), x.min(axis=-1)
    v = maxc
    deltac = maxc - minc
    s = np.where(maxc > 0, deltac / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(deltac, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(r == maxc, bc - gc, np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(deltac == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r2, g2, b2], axis=-1) * hi


class RandomAffine(BaseTransform):
    """Reference python/paddle/vision/transforms/transforms.py:RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale is not None else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 2:
            sh = (random.uniform(self.shear[0], self.shear[1]), 0.0)
        else:
            sh = (random.uniform(self.shear[0], self.shear[1]),
                  random.uniform(self.shear[2], self.shear[3]))
        return affine(arr, angle, (tx, ty), sc, sh,
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    """Reference python/paddle/vision/transforms/transforms.py:RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def get_params(self, width, height, distortion_scale):
        half_w, half_h = width // 2, height // 2
        dx, dy = int(distortion_scale * half_w), int(distortion_scale * half_h)
        tl = (random.randint(0, dx), random.randint(0, dy))
        tr = (random.randint(width - dx - 1, width - 1), random.randint(0, dy))
        br = (random.randint(width - dx - 1, width - 1),
              random.randint(height - dy - 1, height - 1))
        bl = (random.randint(0, dx), random.randint(height - dy - 1, height - 1))
        start = [(0, 0), (width - 1, 0), (width - 1, height - 1), (0, height - 1)]
        return start, [tl, tr, br, bl]

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        start, end = self.get_params(w, h, self.distortion_scale)
        return perspective(arr, start, end, interpolation=self.interpolation,
                           fill=self.fill)


class RandomErasing(BaseTransform):
    """Reference python/paddle/vision/transforms/transforms.py:RandomErasing."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = math.exp(random.uniform(math.log(self.ratio[0]),
                                             math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * aspect)))
            ew = int(round(math.sqrt(target / aspect)))
            if eh < h and ew < w:
                i, j = random.randint(0, h - eh), random.randint(0, w - ew)
                v = (np.random.normal(size=(eh, ew) + arr.shape[2:])
                     if self.value == "random" else self.value)
                return erase(arr, i, j, eh, ew, v, inplace=self.inplace)
        return arr
