"""Vision transforms — reference python/paddle/vision/transforms (numpy/HWC
based host-side preprocessing, feeding the DataLoader pipeline)."""
import numbers
import random

import numpy as np

from ...framework.core import Tensor

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "RandomRotation",
    "Pad", "Grayscale", "to_tensor", "resize", "normalize", "hflip", "vflip",
    "center_crop", "crop", "pad", "adjust_brightness", "adjust_contrast",
    "to_grayscale",
]


def _to_hwc_array(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def to_tensor(img, data_format="CHW"):
    arr = _to_hwc_array(img).astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


def resize(img, size, interpolation="bilinear"):
    import jax
    import jax.numpy as jnp
    arr = _to_hwc_array(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[interpolation]
    out_shape = (oh, ow) + arr.shape[2:]
    return np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape, method=method))


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _to_hwc_array(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_hwc_array(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        if self.padding:
            arr = pad(arr, self.padding)
        h, w = arr.shape[:2]
        th, tw = self.size
        top = random.randint(0, max(h - th, 0))
        left = random.randint(0, max(w - tw, 0))
        return crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return resize(crop(arr, top, left, ch, cw), self.size, self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size, self.interpolation)


def hflip(img):
    return _to_hwc_array(img)[:, ::-1]


def vflip(img):
    return _to_hwc_array(img)[::-1]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _to_hwc_array(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _to_hwc_array(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._value)
    else:
        arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = [mean] * 3 if isinstance(mean, numbers.Number) else mean
        self.std = [std] * 3 if isinstance(std, numbers.Number) else std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_hwc_array(img)
        return arr.transpose(self.order)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_hwc_array(img)
    if isinstance(padding, numbers.Number):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    l, t, r, b = padding
    widths = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(arr, widths, mode=mode, constant_values=fill)
    return np.pad(arr, widths, mode=mode)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


def adjust_brightness(img, brightness_factor):
    arr = _to_hwc_array(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi)


def adjust_contrast(img, contrast_factor):
    arr = _to_hwc_array(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    mean = arr.mean()
    return np.clip((arr - mean) * contrast_factor + mean, 0, hi)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_hwc_array(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_hwc_array(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc_array(img).astype(np.float32)
        if self.value == 0:
            return arr
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = arr.mean(axis=-1, keepdims=True)
        hi = 255.0 if arr.max() > 1.5 else 1.0
        return np.clip(gray + (arr - gray) * f, 0, hi)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        return _to_hwc_array(img)  # hue rotation: HSV roundtrip omitted (rare path)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness), ContrastTransform(contrast),
                           SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        angle = random.uniform(*self.degrees)
        arr = _to_hwc_array(img)
        return ndi.rotate(arr, angle, axes=(0, 1), reshape=False, order=1)


def to_grayscale(img, num_output_channels=1):
    arr = _to_hwc_array(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    return np.repeat(gray[..., None], num_output_channels, axis=-1)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)
