"""YOLOv3-family detectors (BASELINE config "PP-YOLOE / detection").

Reference scope: PaddleDetection's YOLOv3 (DarkNet53 backbone + FPN neck +
per-scale heads) built on the yolo_loss / yolo_box / nms PHI ops that this
repo re-implements in paddle_tpu/vision/ops.py. The model here is an
original TPU-first build: Conv+BN+LeakyReLU blocks run NHWC by default
(channels on the lane dim — see docs/performance.md), heads emit the
NCHW [N, A*(5+C), H, W] tensors the yolo ops expect, and the whole
train step jits into one XLA program.

    model = yolov3_darknet53(num_classes=80)
    losses = model.loss(model(imgs), gt_box, gt_label)     # train
    boxes, scores = model.decode(model(imgs), img_size)    # eval
"""
import jax.numpy as jnp

from ... import nn
from ...framework.core import apply_op
from ...nn.layout import resolve_data_format
from ...tensor.manipulation import concat

__all__ = ["YOLOv3", "yolov3_darknet53", "yolov3_tiny", "DarkNet53"]


class ConvBNLayer(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, data_format="NCHW"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                              bias_attr=False, data_format=data_format)
        self.bn = nn.BatchNorm2D(cout, data_format=data_format)
        self.act = nn.LeakyReLU(0.1)

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _Residual(nn.Layer):
    def __init__(self, ch, data_format="NCHW"):
        super().__init__()
        self.conv1 = ConvBNLayer(ch, ch // 2, k=1, data_format=data_format)
        self.conv2 = ConvBNLayer(ch // 2, ch, k=3, data_format=data_format)

    def forward(self, x):
        return x + self.conv2(self.conv1(x))


class DarkNet53(nn.Layer):
    """DarkNet-53 backbone; returns C3, C4, C5 feature maps (stride 8/16/32).

    stages: 1-2-8-8-4 residual blocks, downsample by stride-2 3x3 convs.
    """

    def __init__(self, data_format="NCHW", depths=(1, 2, 8, 8, 4), width=32):
        super().__init__()
        df = data_format
        w = width
        self.stem = ConvBNLayer(3, w, data_format=df)
        stages = []
        cin = w
        for i, n in enumerate(depths):
            cout = w * (2 ** (i + 1))
            blocks = [ConvBNLayer(cin, cout, stride=2, data_format=df)]
            blocks += [_Residual(cout, data_format=df) for _ in range(n)]
            stages.append(nn.Sequential(*blocks))
            cin = cout
        self.stages = nn.LayerList(stages)
        self.out_channels = [w * 8, w * 16, w * 32]

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for i, stage in enumerate(self.stages):
            x = stage(x)
            if i >= 2:
                feats.append(x)
        return feats        # [C3, C4, C5]


class _YoloDetBlock(nn.Layer):
    """5-conv FPN block: returns (route, tip)."""

    def __init__(self, cin, ch, data_format="NCHW"):
        super().__init__()
        df = data_format
        self.convs = nn.Sequential(
            ConvBNLayer(cin, ch, k=1, data_format=df),
            ConvBNLayer(ch, ch * 2, k=3, data_format=df),
            ConvBNLayer(ch * 2, ch, k=1, data_format=df),
            ConvBNLayer(ch, ch * 2, k=3, data_format=df),
            ConvBNLayer(ch * 2, ch, k=1, data_format=df),
        )
        self.tip = ConvBNLayer(ch, ch * 2, k=3, data_format=df)

    def forward(self, x):
        route = self.convs(x)
        return route, self.tip(route)


_COCO_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
                 59, 119, 116, 90, 156, 198, 373, 326]
_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]   # P5, P4, P3


class YOLOv3(nn.Layer):
    """YOLOv3 head over a 3-scale backbone.

    forward(imgs) -> [p5, p4, p3] raw head outputs, each NCHW
    [N, A*(5+C), H, W] regardless of compute layout (the yolo ops'
    contract). loss() / decode() wrap vision.ops.yolo_loss / yolo_box+nms.
    """

    def __init__(self, backbone=None, num_classes=80,
                 anchors=_COCO_ANCHORS, anchor_masks=_ANCHOR_MASKS,
                 ignore_thresh=0.7, data_format=None):
        super().__init__()
        df = resolve_data_format(data_format, 2)
        self.data_format = df
        self.backbone = backbone or DarkNet53(data_format=df)
        self.num_classes = num_classes
        self.anchors = list(anchors)
        self.anchor_masks = [list(m) for m in anchor_masks]
        self.ignore_thresh = ignore_thresh
        covered = {a for m in self.anchor_masks for a in m}
        if covered != set(range(len(self.anchors) // 2)):
            raise ValueError(
                f"anchor_masks {self.anchor_masks} must cover every anchor "
                f"0..{len(self.anchors) // 2 - 1}: ground-truth boxes whose "
                "best-IoU anchor is unlisted would get no supervision")
        chans = self.backbone.out_channels          # [C3, C4, C5]
        n_scales = len(anchor_masks)
        blocks, outs, routes = [], [], []
        cin = chans[-1]
        for i in range(n_scales):
            # head width follows the backbone (512/256/128 for DarkNet53,
            # proportionally thinner for small backbones)
            ch = max(chans[-1] // 2 // (2 ** i), 16)
            block = _YoloDetBlock(cin, ch, data_format=df)
            na = len(anchor_masks[i])
            out = nn.Conv2D(ch * 2, na * (5 + num_classes), 1,
                            data_format=df)
            blocks.append(block)
            outs.append(out)
            if i < n_scales - 1:
                routes.append(ConvBNLayer(ch, ch // 2, k=1, data_format=df))
                cin = ch // 2 + chans[-2 - i]
        self.blocks = nn.LayerList(blocks)
        self.outs = nn.LayerList(outs)
        self.routes = nn.LayerList(routes)

    def forward(self, x):
        feats = self.backbone(x)          # [C3, C4, C5]
        outputs = []
        route = None
        # deepest-first, only as many scales as the head defines
        feats_rev = list(reversed(feats))[:len(self.blocks)]
        for i, feat in enumerate(feats_rev):            # C5, C4, (C3)
            if route is not None:
                feat = concat([route, feat],
                              axis=3 if self.data_format == "NHWC" else 1)
            route, tip = self.blocks[i](feat)
            head = self.outs[i](tip)
            if self.data_format == "NHWC":
                head = apply_op(lambda v: jnp.transpose(v, (0, 3, 1, 2)),
                                head)
            outputs.append(head)
            if i < len(self.blocks) - 1:
                route = self.routes[i](route)
                route = nn.functional.interpolate(
                    route, scale_factor=2, mode="nearest",
                    data_format=self.data_format)
        return outputs                    # [P5, P4, P3] NCHW

    def loss(self, outputs, gt_box, gt_label, gt_score=None):
        from ...vision.ops import yolo_loss
        total = None
        for i, out in enumerate(outputs):
            l = yolo_loss(out, gt_box, gt_label, self.anchors,
                          self.anchor_masks[i], self.num_classes,
                          ignore_thresh=self.ignore_thresh,
                          downsample_ratio=32 // (2 ** i),
                          gt_score=gt_score)
            l = l.mean()
            total = l if total is None else total + l
        return total

    def decode(self, outputs, img_size, conf_thresh=0.01):
        """Returns (boxes [N, M, 4], scores [N, M, C]) after per-scale
        yolo_box decode + concat; run vision.ops.nms on each image's
        boxes/scores for final detections (host-side, variable length)."""
        from ...vision.ops import yolo_box
        boxes, scores = [], []
        for i, out in enumerate(outputs):
            b, s = yolo_box(out, img_size, self._scale_anchors(i),
                            self.num_classes, conf_thresh,
                            downsample_ratio=32 // (2 ** i))
            boxes.append(b)
            scores.append(s)
        return concat(boxes, axis=1), concat(scores, axis=1)

    def _scale_anchors(self, i):
        flat = []
        for a in self.anchor_masks[i]:
            flat += self.anchors[2 * a: 2 * a + 2]
        return flat


def yolov3_darknet53(num_classes=80, **kw):
    return YOLOv3(num_classes=num_classes, **kw)


def yolov3_tiny(num_classes=20, **kw):
    """Small variant for tests / CPU smoke: thin darknet, 2 scales, the
    6-anchor tiny set (every anchor reachable from one of the two masks)."""
    df = resolve_data_format(kw.pop("data_format", None), 2)
    backbone = DarkNet53(data_format=df, depths=(1, 1, 2, 2, 1), width=8)
    tiny_anchors = [10, 14, 23, 27, 37, 58, 81, 82, 135, 169, 344, 319]
    return YOLOv3(backbone=backbone, num_classes=num_classes,
                  anchors=tiny_anchors,
                  anchor_masks=[[3, 4, 5], [0, 1, 2]], data_format=df, **kw)
