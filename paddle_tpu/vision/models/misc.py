"""SqueezeNet, ShuffleNetV2, DenseNet, GoogLeNet, InceptionV3 — API of the
corresponding reference python/paddle/vision/models/*.py files."""
from ... import nn
from ...nn import functional as F
from ...tensor.manipulation import concat, flatten

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
           "GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


# ---------------------------------------------------------------------------
class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3, 3, padding=1)

    def forward(self, x):
        x = F.relu(self.squeeze(x))
        return concat([F.relu(self.expand1(x)), F.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64), nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
            x = flatten(x, 1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
def _act_layer(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), _act_layer(act))
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), _act_layer(act),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), _act_layer(act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return F.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    _cfgs = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
             0.5: [24, 48, 96, 192, 1024],
             1.0: [24, 116, 232, 464, 1024], 1.5: [24, 176, 352, 704, 1024],
             2.0: [24, 244, 488, 976, 2048]}

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        chs = self._cfgs[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), _act_layer(act))
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = chs[0]
        for i, reps in enumerate([4, 8, 4]):
            out_c = chs[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            units += [_ShuffleUnit(out_c, out_c, 1, act) for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, chs[4], 1, bias_attr=False),
            nn.BatchNorm2D(chs[4]), _act_layer(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.conv5(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(1.0, act="swish", **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


# ---------------------------------------------------------------------------
class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size, dropout):
        super().__init__()
        self.block = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False))
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return concat([x, self.dropout(self.block(x))], axis=1)


class DenseNet(nn.Layer):
    _cfgs = {121: (64, 32, [6, 12, 24, 16]), 161: (96, 48, [6, 12, 36, 24]),
             169: (64, 32, [6, 12, 32, 32]), 201: (64, 32, [6, 12, 48, 32]),
             264: (64, 32, [6, 12, 64, 48])}

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        init_c, growth, blocks = self._cfgs[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1)]
        ch = init_c
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(blocks) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)


# ---------------------------------------------------------------------------
class _InceptionA(nn.Layer):
    """GoogLeNet inception module (BN flavor)."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        def cbr(i, o, k, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, padding=p, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        self.b1 = cbr(in_c, c1, 1)
        self.b3 = nn.Sequential(cbr(in_c, c3r, 1), cbr(c3r, c3, 3, 1))
        self.b5 = nn.Sequential(cbr(in_c, c5r, 1), cbr(c5r, c5, 5, 2))
        self.bp = nn.Sequential(nn.MaxPool2D(3, 1, padding=1), cbr(in_c, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        def cbr(i, o, k, s=1, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, stride=s, padding=p, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        self.stem = nn.Sequential(
            cbr(3, 64, 7, 2, 3), nn.MaxPool2D(3, 2, padding=1),
            cbr(64, 64, 1), cbr(64, 192, 3, 1, 1), nn.MaxPool2D(3, 2, padding=1))
        self.inc3 = nn.Sequential(
            _InceptionA(192, 64, 96, 128, 16, 32, 32),
            _InceptionA(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc4 = nn.Sequential(
            _InceptionA(480, 192, 96, 208, 16, 48, 64),
            _InceptionA(512, 160, 112, 224, 24, 64, 64),
            _InceptionA(512, 128, 128, 256, 24, 64, 64),
            _InceptionA(512, 112, 144, 288, 32, 64, 64),
            _InceptionA(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc5 = nn.Sequential(
            _InceptionA(832, 256, 160, 320, 32, 128, 128),
            _InceptionA(832, 384, 192, 384, 48, 128, 128))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        # reference returns (out, aux1, aux2); aux heads omitted → None
        return x, None, None


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
class InceptionV3(nn.Layer):
    """Simplified InceptionV3 trunk (stem + A/C blocks + classifier);
    aux logits omitted."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        def cbr(i, o, k, s=1, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, stride=s, padding=p, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        self.stem = nn.Sequential(
            cbr(3, 32, 3, 2), cbr(32, 32, 3), cbr(32, 64, 3, 1, 1),
            nn.MaxPool2D(3, 2), cbr(64, 80, 1), cbr(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.mixed = nn.Sequential(
            _InceptionA(192, 64, 48, 64, 64, 96, 32),
            _InceptionA(256, 64, 48, 64, 64, 96, 64),
            _InceptionA(288, 64, 48, 64, 64, 96, 64),
            nn.MaxPool2D(3, 2),
            _InceptionA(288, 192, 128, 192, 128, 192, 192),
            _InceptionA(768, 192, 160, 192, 160, 192, 192),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(768, num_classes)

    def forward(self, x):
        x = self.mixed(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
