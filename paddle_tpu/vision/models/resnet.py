"""ResNet — reference python/paddle/vision/models/resnet.py (API-identical,
implementation on paddle_tpu.nn). `data_format="NHWC"` runs the whole stack
channel-last — the TPU-native layout (channels on the 128-lane minor dim):
NCHW forces XLA to materialize transposes around every conv, which dominates
the step time; NHWC trains at full MXU utilisation. Weights are layout-
independent ([O, I, kH, kW] either way), so checkpoints transfer."""
from ... import nn
from ...nn.layout import resolve_data_format

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
           "wide_resnet50_2", "wide_resnet101_2",
           "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
           "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None, data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = dict(data_format=data_format)
        # custom norm_layer callables keep their pre-NHWC contract: the
        # kwarg is only forwarded when the user opted out of NCHW
        nl = (lambda c: norm_layer(c, **df)) if data_format != "NCHW" else norm_layer
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride, bias_attr=False, **df)
        self.bn1 = nl(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False, **df)
        self.bn2 = nl(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None, data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        df = dict(data_format=data_format)
        nl = (lambda c: norm_layer(c, **df)) if data_format != "NCHW" else norm_layer
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False, **df)
        self.bn1 = nl(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation, bias_attr=False, **df)
        self.bn2 = nl(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False, **df)
        self.bn3 = nl(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True,
                 groups=1, data_format=None, stem_s2d=False):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        data_format = resolve_data_format(data_format, 2)
        self.data_format = data_format
        df = dict(data_format=data_format)
        # TPU stem option: run the 7x7/s2 conv in its exact space-to-depth
        # form (ops/space_to_depth.py) — C=3 starves MXU lanes; NHWC only
        self.stem_s2d = bool(stem_s2d)
        if self.stem_s2d and data_format != "NHWC":
            raise ValueError("stem_s2d requires data_format='NHWC'")
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3, bias_attr=False, **df)
        self.bn1 = nn.BatchNorm2D(self.inplanes, **df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), **df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        downsample = None
        df = dict(data_format=self.data_format)
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, **df),
                nn.BatchNorm2D(planes * block.expansion, **df),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, self.dilation, data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, data_format=self.data_format))
        return nn.Sequential(*layers)

    def forward(self, x):
        if self.stem_s2d:
            from ...framework.core import apply_op
            from ...ops.space_to_depth import space_to_depth_stem_conv
            x = apply_op(space_to_depth_stem_conv, x, self.conv1.weight)
            x = self.relu(self.bn1(x))
        else:
            x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(arch, Block, depth, pretrained, **kwargs):
    model = ResNet(Block, depth, **kwargs)
    if pretrained:
        raise NotImplementedError("no pretrained weight hub in this environment; "
                                  "load converted weights with set_state_dict")
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet("resnet18", BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet("resnet34", BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet("resnet50", BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet("resnet101", BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet("resnet152", BottleneckBlock, 152, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext50_32x4d", BottleneckBlock, 50, pretrained, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext50_64x4d", BottleneckBlock, 50, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext101_32x4d", BottleneckBlock, 101, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext101_64x4d", BottleneckBlock, 101, pretrained, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    kwargs.update(groups=32, width=4)
    return _resnet("resnext152_32x4d", BottleneckBlock, 152, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    kwargs.update(groups=64, width=4)
    return _resnet("resnext152_64x4d", BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet("wide_resnet50_2", BottleneckBlock, 50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet("wide_resnet101_2", BottleneckBlock, 101, pretrained, **kwargs)


# Graph Doctor contract (paddle_tpu.analysis): the op-count signature of
# a lowered resnet50 forward — 53 convolutions (49 block convs + stem +
# 3 downsample projections). A drift here means the architecture (or a
# fusion-blocking rewrite) changed and must be reviewed, not shipped.
GRAPH_CONTRACT = {"convolution": 53}
