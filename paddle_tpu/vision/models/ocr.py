"""PP-OCR-style text models (BASELINE config "PP-OCRv3 / OCR pipeline").

Reference scope: PaddleOCR's recognition (CRNN: conv backbone -> sequence
encoder -> CTC head) and detection (DB: FPN + binarization map) recipes,
re-built TPU-first on this repo's layers: convs run NHWC-capable, the
BiLSTM encoder is the lax.scan RNN stack, and CTC training uses the
log-space forward algorithm in nn.functional.ctc_loss — everything jits
into a single XLA program per step.

    rec = CRNN(num_classes=97)                  # charset + blank
    logits = rec(imgs)                          # [T, N, C] for CTC
    loss = rec.loss(logits, labels, label_lengths)

    det = DBNet()                               # text detection
    prob = det(imgs)                            # [N, 1, H, W] shrink map
    loss = det.loss(prob, gt_map, gt_mask)
"""
import jax.numpy as jnp

from ... import nn
from ...framework.core import apply_op
from ...nn.layout import resolve_data_format

__all__ = ["CRNN", "DBNet", "crnn_mobilenet", "dbnet_mobilenet"]


class _ConvBN(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, padding=None,
                 data_format="NCHW"):
        super().__init__()
        if padding is None:
            padding = k // 2 if isinstance(k, int) else 0
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                              bias_attr=False, data_format=data_format)
        self.bn = nn.BatchNorm2D(cout, data_format=data_format)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CRNN(nn.Layer):
    """Conv stack (height collapsed to 1) -> 2-layer BiLSTM -> CTC logits.

    Input images [N, 3, 32, W] (or NHWC); output [W/4, N, num_classes]
    in the [T, N, C] layout nn.functional.ctc_loss expects. Class 0 is
    the CTC blank (PaddleOCR convention).
    """

    def __init__(self, num_classes=97, hidden_size=96, data_format=None):
        super().__init__()
        df = resolve_data_format(data_format, 2)
        self.data_format = df
        self.num_classes = num_classes
        # height/width strides: H 32 -> 1, W -> W/4
        self.body = nn.Sequential(
            _ConvBN(3, 32, data_format=df),
            nn.MaxPool2D(2, 2, data_format=df),              # 16 x W/2
            _ConvBN(32, 64, data_format=df),
            nn.MaxPool2D(2, 2, data_format=df),              # 8 x W/4
            _ConvBN(64, 128, data_format=df),
            _ConvBN(128, 128, data_format=df),
            nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1),
                         data_format=df),                    # 4 x W/4
            _ConvBN(128, 256, data_format=df),
            nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1),
                         data_format=df),                    # 2 x W/4
            # (2,1) kernel, no padding: collapses H 2->1, W untouched, so
            # the sequence length is exactly W/4 as documented
            _ConvBN(256, 256, k=(2, 1), stride=1, padding=0, data_format=df),
        )
        self.rnn = nn.LSTM(256, hidden_size, num_layers=2,
                           direction="bidirect", time_major=True)
        self.head = nn.Linear(2 * hidden_size, num_classes)

    def forward(self, x):
        feat = self.body(x)
        if self.data_format == "NHWC":
            # [N, 1, W', C] -> [W', N, C]
            seq = apply_op(lambda v: jnp.transpose(v[:, 0], (1, 0, 2)), feat)
        else:
            # [N, C, 1, W'] -> [W', N, C]
            seq = apply_op(lambda v: jnp.transpose(v[:, :, 0], (2, 0, 1)),
                           feat)
        out, _ = self.rnn(seq)
        return self.head(out)                   # [T, N, num_classes]

    def loss(self, logits, labels, label_lengths):
        """CTC loss; input lengths are the full T (no horizontal padding
        convention in the synthetic pipeline)."""
        from ...tensor.creation import full
        T, N = logits.shape[0], logits.shape[1]
        input_lengths = full([N], T, dtype="int32")
        return nn.functional.ctc_loss(logits, labels, input_lengths,
                                      label_lengths, blank=0)

    def decode_greedy(self, logits):
        """Collapse-repeats-then-drop-blanks greedy CTC decode. Returns
        [N, T] int32 with -1 padding (host-side trim to strings)."""
        def _f(lp):
            ids = jnp.argmax(lp, axis=-1).T                  # [N, T]
            prev = jnp.pad(ids[:, :-1], ((0, 0), (1, 0)),
                           constant_values=-1)
            keep = (ids != prev) & (ids != 0)
            order = jnp.argsort(~keep, axis=1, stable=True)  # keepers first
            gathered = jnp.take_along_axis(ids, order, axis=1)
            kept = jnp.take_along_axis(keep, order, axis=1)
            return jnp.where(kept, gathered, -1).astype(jnp.int32)
        return apply_op(_f, logits)


class DBNet(nn.Layer):
    """Differentiable-Binarization-style text detector (PaddleOCR det).

    Light FPN over a 4-stage conv backbone; emits a shrink-probability map
    at 1/4 resolution upsampled to input size. loss() is the DB recipe's
    BCE on the probability map under a supervision mask (the threshold/
    binarization branches collapse into the single map here — the
    inference contract, box extraction from the prob map, is host-side).
    """

    def __init__(self, width=24, data_format=None):
        super().__init__()
        df = resolve_data_format(data_format, 2)
        self.data_format = df
        w = width
        self.stem = _ConvBN(3, w, data_format=df)
        self.stages = nn.LayerList([
            nn.Sequential(_ConvBN(w, w * 2, stride=2, data_format=df),
                          _ConvBN(w * 2, w * 2, data_format=df)),
            nn.Sequential(_ConvBN(w * 2, w * 4, stride=2, data_format=df),
                          _ConvBN(w * 4, w * 4, data_format=df)),
            nn.Sequential(_ConvBN(w * 4, w * 8, stride=2, data_format=df),
                          _ConvBN(w * 8, w * 8, data_format=df)),
            nn.Sequential(_ConvBN(w * 8, w * 8, stride=2, data_format=df),
                          _ConvBN(w * 8, w * 8, data_format=df)),
        ])
        self.laterals = nn.LayerList([
            _ConvBN(c, w * 4, k=1, data_format=df)
            for c in (w * 2, w * 4, w * 8, w * 8)])
        self.out = nn.Conv2D(w * 4, 1, 3, padding=1, data_format=df)

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        # top-down sum with 2x upsampling
        acc = self.laterals[-1](feats[-1])
        for i in range(len(feats) - 2, -1, -1):
            acc = nn.functional.interpolate(
                acc, scale_factor=2, mode="nearest",
                data_format=self.data_format)
            acc = acc + self.laterals[i](feats[i])
        prob = self.out(acc)                       # 1/2 input resolution
        prob = nn.functional.interpolate(
            prob, scale_factor=2, mode="bilinear",
            data_format=self.data_format)
        if self.data_format == "NHWC":
            prob = apply_op(lambda v: jnp.transpose(v, (0, 3, 1, 2)), prob)
        return nn.functional.sigmoid(prob)         # [N, 1, H, W]

    def loss(self, prob, gt_map, mask=None, eps=1e-6):
        """Masked balanced BCE on the shrink map (DB loss's L_s term)."""
        def _f(p, g, m):
            p = jnp.clip(p, eps, 1 - eps)
            bce = -(g * jnp.log(p) + (1 - g) * jnp.log(1 - p))
            if m is None:
                return jnp.mean(bce)
            return jnp.sum(bce * m) / jnp.maximum(jnp.sum(m), 1.0)
        if mask is None:
            return apply_op(lambda p, g: _f(p, g, None), prob, gt_map)
        return apply_op(_f, prob, gt_map, mask)


def crnn_mobilenet(num_classes=97, **kw):
    return CRNN(num_classes=num_classes, **kw)


def dbnet_mobilenet(**kw):
    return DBNet(**kw)


# Graph Doctor contract (paddle_tpu.analysis): CRNN's lowered forward is
# 6 convolutions (backbone) + 9 dot_generals (2-layer BiLSTM cells + CTC
# head); the only legal activation transpose is the sequence-major flip.
GRAPH_CONTRACT = {"convolution": 6, "dot_general": 9}
