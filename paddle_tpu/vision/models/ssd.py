"""SSD single-shot detector — TPU-first detection training.

Reference scope: PaddleCV's SSD recipe (prior boxes + MultiBoxLoss over
fluid prior_box/multiclass_nms ops). Unlike proposal-based detectors, SSD
is ALL static shapes — priors are fixed at build time, ground truth is
matched to priors with dense IoU, and hard negative mining is a top-k —
so the entire training step (forward + match + loss + backward + update)
compiles into one XLA program with no host round-trips.

    model = ssd_lite(num_classes=20, image_size=128)
    loc, conf = model(imgs)
    loss = model.loss(loc, conf, gt_box, gt_label)   # fully jittable
    boxes, scores = model.decode(loc, conf)          # for host-side NMS
"""
import math

import numpy as np

import jax
import jax.numpy as jnp

from ... import nn
from ...framework.core import apply_op
from ...nn.layout import resolve_data_format

__all__ = ["SSD", "ssd_lite", "make_prior_boxes"]


def make_prior_boxes(feat_sizes, min_ratio=0.15, max_ratio=0.9,
                     aspect_ratios=(2.0,)):
    """Static prior boxes (cx, cy, w, h normalized) — reference fluid
    prior_box op. One scale per feature map (linear min→max), plus the
    geometric-mean extra scale and the given aspect ratios."""
    n_maps = len(feat_sizes)
    scales = [min_ratio + (max_ratio - min_ratio) * i / max(n_maps - 1, 1)
              for i in range(n_maps)]
    scales.append(min(1.0, scales[-1] * (scales[-1] / max(scales[-2], 1e-6))
                      if n_maps > 1 else 1.0))
    priors = []
    for m, fs in enumerate(feat_sizes):
        s = scales[m]
        s_next = scales[m + 1]
        whs = [(s, s), (math.sqrt(s * s_next),) * 2]
        for ar in aspect_ratios:
            whs.append((s * math.sqrt(ar), s / math.sqrt(ar)))
            whs.append((s / math.sqrt(ar), s * math.sqrt(ar)))
        for y in range(fs):
            for x in range(fs):
                cx, cy = (x + 0.5) / fs, (y + 0.5) / fs
                for w, h in whs:
                    priors.append((cx, cy, w, h))
    return np.clip(np.asarray(priors, np.float32), 0.0, 1.0)


def _priors_per_cell(aspect_ratios):
    return 2 + 2 * len(aspect_ratios)


class SSD(nn.Layer):
    """Backbone stages -> per-scale (loc, conf) heads over static priors.

    gt_box: [N, B, cx cy w h] normalized (w=h=0 pads); gt_label: [N, B]
    class ids (0..C-1; the conf head's class 0 is background, so targets
    are shifted by +1 internally, mirroring the reference MultiBoxLoss).
    """

    def __init__(self, num_classes=20, image_size=128, width=32,
                 aspect_ratios=(2.0,), variances=(0.1, 0.1, 0.2, 0.2),
                 neg_pos_ratio=3.0, data_format=None):
        super().__init__()
        df = resolve_data_format(data_format, 2)
        self.data_format = df
        self.num_classes = num_classes
        self.variances = variances
        self.neg_pos_ratio = neg_pos_ratio
        w = width
        act = nn.ReLU

        def block(cin, cout, stride):
            return nn.Sequential(
                nn.Conv2D(cin, cout, 3, stride=stride, padding=1,
                          bias_attr=False, data_format=df),
                nn.BatchNorm2D(cout, data_format=df), act())

        # 4 detection scales: /8, /16, /32, /64
        self.stem = nn.Sequential(block(3, w, 2), block(w, w * 2, 2))
        self.stages = nn.LayerList([
            nn.Sequential(block(w * 2, w * 4, 2), block(w * 4, w * 4, 1)),
            nn.Sequential(block(w * 4, w * 8, 2), block(w * 8, w * 8, 1)),
            nn.Sequential(block(w * 8, w * 8, 2), block(w * 8, w * 8, 1)),
            nn.Sequential(block(w * 8, w * 8, 2), block(w * 8, w * 8, 1)),
        ])
        chans = [w * 4, w * 8, w * 8, w * 8]
        A = _priors_per_cell(aspect_ratios)
        self.loc_heads = nn.LayerList([
            nn.Conv2D(c, A * 4, 3, padding=1, data_format=df) for c in chans])
        self.conf_heads = nn.LayerList([
            nn.Conv2D(c, A * (num_classes + 1), 3, padding=1, data_format=df)
            for c in chans])
        # every stride-2 conv (k=3, p=1) yields ceil(in/2); walk the six
        # downsamples so priors match the head maps for ANY image size
        size = image_size
        feat_sizes = []
        for i in range(6):
            size = (size + 1) // 2
            if i >= 2:                       # /8, /16, /32, /64 scales
                feat_sizes.append(size)
        self._priors = make_prior_boxes(feat_sizes,
                                        aspect_ratios=aspect_ratios)

    @property
    def priors(self):
        return self._priors                    # [P, 4] numpy (static)

    def forward(self, x):
        x = self.stem(x)
        locs, confs = [], []
        C1 = self.num_classes + 1
        for stage, lh, ch in zip(self.stages, self.loc_heads,
                                 self.conf_heads):
            x = stage(x)
            loc = lh(x)
            conf = ch(x)
            if self.data_format == "NCHW":
                loc = apply_op(lambda v: jnp.transpose(v, (0, 2, 3, 1)), loc)
                conf = apply_op(lambda v: jnp.transpose(v, (0, 2, 3, 1)),
                                conf)
            locs.append(apply_op(
                lambda v: v.reshape(v.shape[0], -1, 4), loc))
            confs.append(apply_op(
                lambda v, c=C1: v.reshape(v.shape[0], -1, c), conf))
        from ...tensor.manipulation import concat
        return concat(locs, axis=1), concat(confs, axis=1)  # [N,P,4],[N,P,C+1]

    # -- training ---------------------------------------------------------

    def loss(self, loc_pred, conf_pred, gt_box, gt_label):
        """Dense IoU matching + smooth-L1 loc + CE conf with 3:1 hard
        negative mining — the reference MultiBoxLoss, as pure jnp."""
        pri = jnp.asarray(self._priors)
        var = jnp.asarray(self.variances, jnp.float32)
        npr = self.neg_pos_ratio

        def _f(loc, conf, gbox, glabel):
            N, P, _ = loc.shape
            B = gbox.shape[1]
            valid = (gbox[..., 2] > 0) & (gbox[..., 3] > 0)     # [N, B]
            # corners
            p1 = pri[:, :2] - pri[:, 2:] / 2
            p2 = pri[:, :2] + pri[:, 2:] / 2
            g1 = gbox[..., :2] - gbox[..., 2:] / 2
            g2 = gbox[..., :2] + gbox[..., 2:] / 2
            ix = jnp.maximum(0.0, jnp.minimum(p2[None, None, :, 0], g2[..., None, 0])
                             - jnp.maximum(p1[None, None, :, 0], g1[..., None, 0]))
            iy = jnp.maximum(0.0, jnp.minimum(p2[None, None, :, 1], g2[..., None, 1])
                             - jnp.maximum(p1[None, None, :, 1], g1[..., None, 1]))
            inter = ix * iy                                     # [N, B, P]
            area_p = (pri[:, 2] * pri[:, 3])[None, None, :]
            area_g = (gbox[..., 2] * gbox[..., 3])[..., None]
            iou = jnp.where(valid[..., None],
                            inter / jnp.maximum(area_p + area_g - inter, 1e-9),
                            0.0)
            best_gt = jnp.argmax(iou, axis=1)                   # [N, P]
            best_iou = jnp.max(iou, axis=1)
            # every gt claims its best prior (bipartite step); padded gt
            # rows scatter out of range (dropped) so they can never
            # clobber a real object's claim at prior 0
            best_prior = jnp.argmax(iou, axis=2)                # [N, B]
            safe_bp = jnp.where(valid, best_prior, P)
            claimed = jax.vmap(
                lambda bp: jnp.zeros((P,), bool).at[bp].set(True,
                                                            mode="drop")
            )(safe_bp)
            forced_gt = jax.vmap(
                lambda bp: jnp.full((P,), -1, jnp.int32)
                .at[bp].set(jnp.arange(B, dtype=jnp.int32), mode="drop")
            )(safe_bp)
            gt_idx = jnp.where(forced_gt >= 0, forced_gt,
                               best_gt.astype(jnp.int32))
            positive = claimed | (best_iou >= 0.5)
            # gather matched gt
            take = jax.vmap(lambda arr, idx: arr[idx])
            mbox = take(gbox, gt_idx)                           # [N, P, 4]
            mlab = take(glabel.astype(jnp.int32), gt_idx)
            # encode loc targets (center-size with variances)
            t_xy = (mbox[..., :2] - pri[None, :, :2]) / \
                (pri[None, :, 2:] * var[:2])
            t_wh = jnp.log(jnp.maximum(mbox[..., 2:], 1e-6)
                           / pri[None, :, 2:]) / var[2:]
            t = jnp.concatenate([t_xy, t_wh], axis=-1)
            d = loc.astype(jnp.float32) - t
            smooth = jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                               jnp.abs(d) - 0.5).sum(-1)
            n_pos = jnp.maximum(positive.sum(axis=1), 1)
            loss_loc = (smooth * positive).sum(axis=1)
            # conf: target 0 = background, gt classes shifted +1
            target = jnp.where(positive, mlab + 1, 0)
            logp = jax.nn.log_softmax(conf.astype(jnp.float32), axis=-1)
            ce = -jnp.take_along_axis(logp, target[..., None],
                                      axis=-1)[..., 0]          # [N, P]
            # hard negative mining: top (npr * n_pos) background losses
            neg_ce = jnp.where(positive, -jnp.inf, ce)
            order = jnp.argsort(-neg_ce, axis=1)
            rank = jnp.argsort(order, axis=1)
            n_neg = jnp.minimum((npr * n_pos).astype(jnp.int32),
                                P - n_pos.astype(jnp.int32))
            negative = rank < n_neg[:, None]
            loss_conf = (ce * (positive | negative)).sum(axis=1)
            return jnp.mean((loss_loc + loss_conf) / n_pos)

        return apply_op(_f, loc_pred, conf_pred, gt_box, gt_label)

    # -- inference --------------------------------------------------------

    def decode(self, loc_pred, conf_pred):
        """Decode priors + offsets -> (boxes [N,P,4] xyxy normalized,
        scores [N,P,C]); feed per-image slices to vision.ops.nms."""
        pri = jnp.asarray(self._priors)
        var = jnp.asarray(self.variances, jnp.float32)

        def _f(loc, conf):
            loc = loc.astype(jnp.float32)
            cxy = pri[None, :, :2] + loc[..., :2] * var[:2] * pri[None, :, 2:]
            wh = pri[None, :, 2:] * jnp.exp(loc[..., 2:] * var[2:])
            boxes = jnp.concatenate([cxy - wh / 2, cxy + wh / 2], axis=-1)
            scores = jax.nn.softmax(conf.astype(jnp.float32), axis=-1)[..., 1:]
            return jnp.clip(boxes, 0.0, 1.0), scores

        return apply_op(_f, loc_pred, conf_pred)   # one dispatch, two outs


def ssd_lite(num_classes=20, image_size=128, **kw):
    return SSD(num_classes=num_classes, image_size=image_size, **kw)
