"""MobileNet V1/V2/V3 — API of reference python/paddle/vision/models/
mobilenetv{1,2,3}.py. Depthwise convs lower to grouped
lax.conv_general_dilated (feature_group_count=channels)."""
from ... import nn
from ...nn import functional as F

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, activation=nn.ReLU):
        pad = (kernel - 1) // 2
        layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(out_c)]
        if activation is not None:
            layers.append(activation())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                _ConvBNReLU(in_c, in_c, 3, stride=stride, groups=in_c),
                _ConvBNReLU(in_c, out_c, 1))

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        for in_c, out_c, s in cfg:
            layers.append(dw_sep(c(in_c), c(out_c), s))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, activation=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        activation=nn.ReLU6),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, activation=nn.ReLU6)]
        for t, ch, n, s in cfg:
            out_c = _make_divisible(ch * scale)
            for i in range(n):
                layers.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, 1, activation=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "HS" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers.append(_ConvBNReLU(in_c, exp_c, 1, activation=act_layer))
        layers.append(_ConvBNReLU(exp_c, exp_c, kernel, stride=stride,
                                  groups=exp_c, activation=act_layer))
        if use_se:
            layers.append(_SqueezeExcite(exp_c, _make_divisible(exp_c // 4)))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False), nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


_V3_SMALL = [
    # k, exp, out, se, act, stride
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]
_V3_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, activation=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(_MBV3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        last_c = _make_divisible(last_exp * scale)
        layers.append(_ConvBNReLU(in_c, last_c, 1, activation=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            out_dim = 1024 if last_exp == 576 else 1280
            self.classifier = nn.Sequential(
                nn.Linear(last_c, out_dim), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(out_dim, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no weight hub; load with set_state_dict")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no weight hub; load with set_state_dict")
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no weight hub; load with set_state_dict")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no weight hub; load with set_state_dict")
    return MobileNetV3Large(scale=scale, **kwargs)
