"""Vision models — reference python/paddle/vision/models/__init__.py.
(alexnet/vgg/mobilenet/... land as the catalog widens; resnet + lenet first.)"""
from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
)
