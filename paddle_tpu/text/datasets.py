"""Reference python/paddle/text/datasets/ — the dataset classes live in
paddle_tpu.text; this submodule preserves the reference import path
(`from paddle.text.datasets import Conll05st`)."""
from . import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14,
               WMT16)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
