"""paddle_tpu.text — API of reference python/paddle/text (dataset loaders +
viterbi_decode). Zero-egress: corpus datasets load from local paths."""
import os

import numpy as np

from ..framework.core import Tensor, apply_op
from ..io import Dataset

__all__ = ["Imdb", "Conll05st", "UCIHousing", "WMT14", "WMT16", "Movielens",
           "Imikolov", "ViterbiDecoder", "viterbi_decode"]


class _LocalCorpus(Dataset):
    """Reads a local .npz of (data, labels); synthesizes when absent."""

    def __init__(self, data_file=None, mode="train", n=200, dim=16, n_classes=2, seed=0):
        if data_file and os.path.exists(data_file):
            raw = np.load(data_file, allow_pickle=True)
            self.data, self.labels = raw["data"], raw["labels"]
        else:
            rng = np.random.RandomState(seed if mode == "train" else seed + 1)
            self.data = rng.randint(0, 5000, (n, dim)).astype("int64")
            self.labels = rng.randint(0, n_classes, n).astype("int64")

    def __getitem__(self, idx):
        return self.data[idx], self.labels[idx]

    def __len__(self):
        return len(self.data)


class Imdb(_LocalCorpus):
    """IMDB sentiment (reference text/datasets/imdb.py). A real aclImdb
    tarball given as data_file is parsed by dataset/imdb.py (tokenize +
    frequency word dict); .npz and synthetic fallbacks otherwise."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        if download and data_file is None:
            raise NotImplementedError("zero-egress: pass local data_file")
        import tarfile
        if data_file and os.path.exists(data_file) \
                and tarfile.is_tarfile(data_file):
            from ..dataset import imdb as imdb_reader
            self.word_idx = imdb_reader.build_dict(data_file, cutoff=cutoff)
            reader = (imdb_reader.train if mode == "train"
                      else imdb_reader.test)(word_idx=self.word_idx,
                                             data_file=data_file)
            pairs = list(reader())
            self.data = [np.asarray(ids, "int64") for ids, _ in pairs]
            self.labels = np.asarray([lab for _, lab in pairs], "int64")
            return
        super().__init__(data_file, mode)


class _TupleCorpus(Dataset):
    """Samples are tuples whose every element maps to an np array
    (reference text datasets' __getitem__ convention)."""

    def __getitem__(self, idx):
        return tuple(np.array(x) for x in self.data[idx])

    def __len__(self):
        return len(self.data)


class Imikolov(_TupleCorpus):
    """PTB language-model corpus (reference text/datasets/imikolov.py).
    A real simple-examples tarball given as data_file is parsed: the word
    dict builds from ptb.train.txt + ptb.valid.txt with per-line <s>/<e>
    counts, freq > min_word_freq, sorted (-freq, word), <unk> last;
    NGRAM mode yields window tuples, SEQ mode ((<s>+ids), (ids+<e>))."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        import tarfile
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        assert self.mode in ("train", "valid", "test"), \
            f"mode should be 'train', 'valid' or 'test', got {mode!r}"
        self.window_size = window_size
        if data_file and os.path.exists(data_file):
            if not tarfile.is_tarfile(data_file):
                raise ValueError(
                    f"{data_file!r} exists but is not a PTB "
                    "simple-examples tarball — refusing to silently "
                    "train on synthetic data")
            # ONE TarFile for dict build + load: gzip tars re-inflate
            # from byte 0 on every fresh open
            with tarfile.open(data_file) as tf:
                names = set(tf.getnames())
                self.word_idx = self._build_dict(tf, names, min_word_freq)
                self._load(tf, names)
            return
        self._synth_init(data_file, mode, window_size)

    def _synth_init(self, data_file, mode, window_size):
        # synthetic stand-in yields the SAME sample shapes as the real
        # path: window tuples for NGRAM, (src, trg) id lists for SEQ
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.word_idx = {f"w{i}": i for i in range(5000)}
        self.word_idx.update({"<s>": 5000, "<e>": 5001, "<unk>": 5002})
        self.data = []
        for _ in range(200):
            if self.data_type == "NGRAM":
                w = max(2, window_size if window_size > 0 else 5)
                self.data.append(tuple(rng.randint(0, 5000, w).tolist()))
            else:
                n = int(rng.randint(4, 20))
                ids = rng.randint(0, 5000, n).tolist()
                self.data.append(([self.word_idx["<s>"]] + ids,
                                  ids + [self.word_idx["<e>"]]))

    @staticmethod
    def _member(tf, names, name):
        for cand in (name, name[2:] if name.startswith("./") else "./" + name):
            if cand in names:
                return tf.extractfile(cand)
        raise KeyError(name)

    def _build_dict(self, tf, names, cutoff):
        freq = {}
        for split in ("train", "valid"):
            f = self._member(
                tf, names, f"./simple-examples/data/ptb.{split}.txt")
            for line in f:
                for w in line.decode().strip().split():
                    freq[w] = freq.get(w, 0) + 1
                freq["<s>"] = freq.get("<s>", 0) + 1
                freq["<e>"] = freq.get("<e>", 0) + 1
        freq.pop("<unk>", None)        # reference reserves the last id
        items = sorted(((c, w) for w, c in freq.items() if c > cutoff),
                       key=lambda cw: (-cw[0], cw[1]))
        word_idx = {w: i for i, (_, w) in enumerate(items)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self, tf, names):
        self.data = []
        unk = self.word_idx["<unk>"]
        f = self._member(
            tf, names, f"./simple-examples/data/ptb.{self.mode}.txt")
        for line in f:
            words = line.decode().strip().split()
            if self.data_type == "NGRAM":
                assert self.window_size > -1, "Invalid gram length"
                toks = ["<s>"] + words + ["<e>"]
                if len(toks) >= self.window_size:
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(
                            tuple(ids[i - self.window_size:i]))
            elif self.data_type == "SEQ":
                ids = [self.word_idx.get(w, unk) for w in words]
                src = [self.word_idx.get("<s>", unk)] + ids
                trg = ids + [self.word_idx.get("<e>", unk)]
                if self.window_size > 0 and len(src) > self.window_size:
                    continue
                self.data.append((src, trg))
            else:
                raise ValueError(f"unknown data_type {self.data_type}")


class Conll05st(_TupleCorpus):
    """CoNLL-2005 SRL test set (reference text/datasets/conll05.py).
    Real inputs: the conll05st-tests tarball (words.gz + props.gz under
    conll05st-release/test.wsj/) plus word/verb/target dict files.
    Props bracket tags expand to BIO; each (sentence, predicate) pair
    yields the reference 9-field sample (word ids, five ctx-window id
    columns, predicate ids, verb-region mark, BIO label ids). UNK id 0.
    Label-dict tag order is SORTED here (the reference iterates a set —
    nondeterministic); 'O' is last either way."""

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 emb_file=None, mode="train", download=False):
        import tarfile
        if data_file and os.path.exists(data_file):
            if not (tarfile.is_tarfile(data_file) and word_dict_file
                    and verb_dict_file and target_dict_file):
                raise ValueError(
                    "Conll05st needs the conll05st-tests tarball PLUS "
                    "word/verb/target dict files (all local paths)")
            self.word_dict = self._load_dict(word_dict_file)
            self.predicate_dict = self._load_dict(verb_dict_file)
            self.label_dict = self._load_label_dict(target_dict_file)
            self.emb_file = emb_file
            self._load_anno(data_file)
            return
        # synthetic stand-in with the same 9-field shape
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.word_dict = {f"w{i}": i for i in range(5000)}
        self.predicate_dict = {f"v{i}": i for i in range(100)}
        self.label_dict = {"B-A0": 0, "I-A0": 1, "B-V": 2, "I-V": 3, "O": 4}
        self.data = []
        for _ in range(100):
            n = int(rng.randint(4, 20))
            row = [rng.randint(0, 5000, n).tolist() for _ in range(6)]
            row += [[int(rng.randint(0, 100))] * n,
                    rng.randint(0, 2, n).tolist(),
                    rng.randint(0, 5, n).tolist()]
            self.data.append(tuple(row))

    @staticmethod
    def _load_dict(filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        tags = set()
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        for tag in sorted(tags):
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    @staticmethod
    def _expand_bio(lbl):
        """Bracket tags ('(A0*', '*', '*)', '(V*)') -> BIO sequence."""
        out, cur, inside = [], "O", False
        for tok in lbl:
            if tok == "*":
                out.append("I-" + cur if inside else "O")
            elif tok == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in tok and ")" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = True
            else:
                raise RuntimeError(f"Unexpected label: {tok}")
        return out

    def _load_anno(self, data_file):
        import gzip
        import tarfile
        samples = []
        with tarfile.open(data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentence, seg = [], []
                for word, props in zip(words_file, props_file):
                    word = word.decode().strip()
                    cols = props.decode().strip().split()
                    if cols:
                        sentence.append(word)
                        seg.append(cols)
                        continue
                    if seg:                      # end of sentence
                        columns = list(zip(*seg))
                        verbs = [v for v in columns[0] if v != "-"]
                        for i, lbl in enumerate(columns[1:]):
                            samples.append(
                                (list(sentence), verbs[i],
                                 self._expand_bio(lbl)))
                    sentence, seg = [], []
        self.data = [self._features(*s) for s in samples]

    def _features(self, sentence, predicate, labels):
        n = len(sentence)
        v = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, name in ((-2, "n2"), (-1, "n1"), (0, "0"),
                          (1, "p1"), (2, "p2")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = "bos" if off < 0 else "eos"
        wd, UNK = self.word_dict, self.UNK_IDX
        word_idx = [wd.get(w, UNK) for w in sentence]
        row = [word_idx]
        for name in ("n2", "n1", "0", "p1", "p2"):
            row.append([wd.get(ctx[name], UNK)] * n)
        row.append([self.predicate_dict.get(predicate)] * n)
        row.append(mark)
        row.append([self.label_dict.get(w) for w in labels])
        return tuple(row)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        """Pretrained word embeddings (reference get_embedding):
        whitespace-separated floats, one row per word-dict entry."""
        if not getattr(self, "emb_file", None):
            raise ValueError(
                "no emb_file was provided to Conll05st(...)")
        return np.loadtxt(self.emb_file, dtype="float32")


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=False):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            raw = rng.rand(200, 14).astype("float32")
        self.features = raw[:, :13].astype("float32")
        self.target = raw[:, 13:].astype("float32")

    def __getitem__(self, idx):
        return self.features[idx], self.target[idx]

    def __len__(self):
        return len(self.features)


class WMT14(_TupleCorpus):
    """WMT14 en-fr translation subset (reference text/datasets/wmt14.py).
    A real wmt14.tgz given as data_file is parsed: `*src.dict` /
    `*trg.dict` members (one word per line, first dict_size kept) and
    tab-separated parallel lines in members ending '{mode}/{mode}'.
    Samples: (src_ids with <s>/<e>, <s>+trg_ids, trg_ids+<e>); pairs
    longer than 80 tokens dropped. UNK id is 2 (reference constant)."""

    UNK_IDX = 2
    START, END = "<s>", "<e>"

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        import tarfile
        mode = mode.lower()
        assert mode in ("train", "test", "gen"), \
            f"mode should be 'train', 'test' or 'gen', got {mode!r}"
        self.mode = mode
        if data_file and os.path.exists(data_file):
            if not tarfile.is_tarfile(data_file):
                raise ValueError(
                    f"{data_file!r} exists but is not a wmt14 tarball — "
                    "refusing to silently train on synthetic data")
            assert dict_size > 0, "dict_size should be a positive number"
            self._load_real(data_file, dict_size)
            return
        # synthetic stand-in, same 3-field sample shape
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.src_dict = {f"w{i}": i for i in range(3000)}
        self.trg_dict = {self.START: 0, self.END: 1,
                         **{f"v{i}": i + 3 for i in range(3000)}}
        self.data = []
        for _ in range(200):
            ns, nt = int(rng.randint(3, 30)), int(rng.randint(3, 30))
            src = rng.randint(3, 3000, ns).tolist()
            trg = rng.randint(3, 3000, nt).tolist()
            self.data.append((src, [0] + trg, trg + [1]))

    def _load_real(self, data_file, dict_size):
        import tarfile

        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.decode().strip()] = i
            return out

        self.data = []
        with tarfile.open(data_file, mode="r") as f:
            members = f.getmembers()
            src_d = [m for m in members if m.name.endswith("src.dict")]
            trg_d = [m for m in members if m.name.endswith("trg.dict")]
            assert len(src_d) == 1 and len(trg_d) == 1, \
                "archive must hold exactly one src.dict and one trg.dict"
            self.src_dict = to_dict(f.extractfile(src_d[0]), dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_d[0]), dict_size)
            suffix = f"{self.mode}/{self.mode}"
            matched = [m for m in members if m.name.endswith(suffix)]
            if not matched:
                raise ValueError(
                    f"no member ending {suffix!r} in {data_file!r} — "
                    f"the archive has no {self.mode} split")
            for m in matched:
                for line in f.extractfile(m):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = [self.START] + parts[0].split() + [self.END]
                    src = [self.src_dict.get(w, self.UNK_IDX)
                           for w in src_words]
                    trg = [self.trg_dict.get(w, self.UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.data.append(
                        (src, [self.trg_dict[self.START]] + trg,
                         trg + [self.trg_dict[self.END]]))


class WMT16(WMT14):
    """WMT16 en-de (reference text/datasets/wmt16.py signature:
    src_dict_size/trg_dict_size/lang, modes train/test/val). Shares the
    WMT14 sample contract (src_ids, trg_ids, trg_ids_next); the parser
    expects a wmt14-layout tarball (src.dict/trg.dict + parallel
    '{mode}/{mode}' members) — the reference instead builds vocabularies
    from the raw corpus, accepted divergence documented here."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        mode = mode.lower()
        assert mode in ("train", "test", "val"), \
            f"mode should be 'train', 'test' or 'val', got {mode!r}"
        self.lang = lang
        dict_size = max(int(src_dict_size), int(trg_dict_size))
        wmt14_mode = mode if mode != "val" else "test"
        super().__init__(data_file=data_file, mode=wmt14_mode,
                         dict_size=dict_size if data_file else -1,
                         download=download)
        self.mode = mode


class Movielens(_TupleCorpus):
    """ml-1m recsys corpus (reference text/datasets/movielens.py). A real
    ml-1m zip given as data_file is parsed: movies.dat / users.dat /
    ratings.dat ('::'-separated, latin-1), sample =
    (uid, gender, age_idx, job, mov_id, category_ids, title_word_ids,
    [rating*2-5]) with a seeded random train/test split."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        import zipfile
        mode = mode.lower()
        assert mode in ("train", "test"), \
            f"mode should be 'train' or 'test', got {mode!r}"
        if data_file and os.path.exists(data_file):
            if not zipfile.is_zipfile(data_file):
                raise ValueError(
                    f"{data_file!r} exists but is not an ml-1m zip — "
                    "refusing to silently train on synthetic data")
            self._load_real(data_file, mode, test_ratio, rand_seed)
            return
        # synthetic stand-in with the SAME 8-field sample shape
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.categories_dict = {f"c{i}": i for i in range(18)}
        self.movie_title_dict = {f"t{i}": i for i in range(500)}
        self.data = [
            (int(rng.randint(1, 6041)), int(rng.randint(0, 2)),
             int(rng.randint(0, 7)), int(rng.randint(0, 21)),
             int(rng.randint(1, 3953)),
             rng.randint(0, 18, rng.randint(1, 4)).tolist(),
             rng.randint(0, 500, rng.randint(1, 5)).tolist(),
             [float(rng.randint(1, 6)) * 2 - 5.0])
            for _ in range(200)]

    def _load_real(self, data_file, mode, test_ratio, rand_seed):
        import re as _re
        import zipfile
        title_rx = _re.compile(r"(.*)\s*\(\d{4}\)\s*$")
        movies, title_words, cats = {}, set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, categories = \
                        line.decode("latin-1").strip().split("::")
                    cat_list = categories.split("|")
                    cats.update(cat_list)
                    m = title_rx.match(title)
                    title = (m.group(1) if m else title).strip()
                    movies[int(mid)] = (cat_list, title)
                    title_words.update(w.lower() for w in title.split())
            self.categories_dict = {c: i for i, c in enumerate(sorted(cats))}
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            users = {}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job = \
                        line.decode("latin-1").strip().split("::")[:4]
                    users[int(uid)] = (0 if gender == "M" else 1,
                                       self.AGES.index(int(age)), int(job))
            self.max_user_id_ = max(users) if users else 0
            self.max_movie_id_ = max(movies) if movies else 0
            rng = np.random.RandomState(rand_seed)
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    uid, mid, rating = \
                        line.decode("latin-1").strip().split("::")[:3]
                    if (rng.random_sample() < test_ratio) != (mode == "test"):
                        continue
                    uid, mid = int(uid), int(mid)
                    cat_list, title = movies[mid]
                    g, a, j = users[uid]
                    self.data.append((
                        uid, g, a, j, mid,
                        [self.categories_dict[c] for c in cat_list],
                        [self.movie_title_dict[w.lower()]
                         for w in title.split()],
                        [float(rating) * 2 - 5.0]))


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference text/viterbi_decode.py) via lax.scan."""
    import jax
    import jax.numpy as jnp

    def _f(emis, trans):
        B, T, N = emis.shape

        def step(carry, e_t):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None]  # [B, from, to]
            best = jnp.max(cand, axis=1) + e_t
            idx = jnp.argmax(cand, axis=1)
            return best, idx

        score0 = emis[:, 0]
        scores, backptrs = jax.lax.scan(step, score0, jnp.swapaxes(emis[:, 1:], 0, 1))
        last_best = jnp.argmax(scores, axis=-1)  # [B]

        def backtrack(carry, ptr_t):
            cur = carry
            prev = jnp.take_along_axis(ptr_t, cur[:, None], axis=1)[:, 0]
            return prev, cur

        _, path = jax.lax.scan(backtrack, last_best, backptrs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path, 0, 1),
                                last_best[:, None]], axis=1)
        return jnp.max(scores, axis=-1), path
    return apply_op(_f, potentials, transition_params)


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from . import datasets  # noqa: F401,E402  (reference paddle.text.datasets path)
