"""paddle_tpu.text — API of reference python/paddle/text (dataset loaders +
viterbi_decode). Zero-egress: corpus datasets load from local paths."""
import os

import numpy as np

from ..framework.core import Tensor, apply_op
from ..io import Dataset

__all__ = ["Imdb", "Conll05st", "UCIHousing", "WMT14", "WMT16", "Movielens",
           "Imikolov", "ViterbiDecoder", "viterbi_decode"]


class _LocalCorpus(Dataset):
    """Reads a local .npz of (data, labels); synthesizes when absent."""

    def __init__(self, data_file=None, mode="train", n=200, dim=16, n_classes=2, seed=0):
        if data_file and os.path.exists(data_file):
            raw = np.load(data_file, allow_pickle=True)
            self.data, self.labels = raw["data"], raw["labels"]
        else:
            rng = np.random.RandomState(seed if mode == "train" else seed + 1)
            self.data = rng.randint(0, 5000, (n, dim)).astype("int64")
            self.labels = rng.randint(0, n_classes, n).astype("int64")

    def __getitem__(self, idx):
        return self.data[idx], self.labels[idx]

    def __len__(self):
        return len(self.data)


class Imdb(_LocalCorpus):
    """IMDB sentiment (reference text/datasets/imdb.py). A real aclImdb
    tarball given as data_file is parsed by dataset/imdb.py (tokenize +
    frequency word dict); .npz and synthetic fallbacks otherwise."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        if download and data_file is None:
            raise NotImplementedError("zero-egress: pass local data_file")
        import tarfile
        if data_file and os.path.exists(data_file) \
                and tarfile.is_tarfile(data_file):
            from ..dataset import imdb as imdb_reader
            self.word_idx = imdb_reader.build_dict(data_file, cutoff=cutoff)
            reader = (imdb_reader.train if mode == "train"
                      else imdb_reader.test)(word_idx=self.word_idx,
                                             data_file=data_file)
            pairs = list(reader())
            self.data = [np.asarray(ids, "int64") for ids, _ in pairs]
            self.labels = np.asarray([lab for _, lab in pairs], "int64")
            return
        super().__init__(data_file, mode)


class Imikolov(_LocalCorpus):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        super().__init__(data_file, mode, dim=window_size)


class Conll05st(_LocalCorpus):
    pass


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=False):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            raw = rng.rand(200, 14).astype("float32")
        self.features = raw[:, :13].astype("float32")
        self.target = raw[:, 13:].astype("float32")

    def __getitem__(self, idx):
        return self.features[idx], self.target[idx]

    def __len__(self):
        return len(self.features)


class WMT14(_LocalCorpus):
    pass


class WMT16(_LocalCorpus):
    pass


class Movielens(_LocalCorpus):
    pass


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference text/viterbi_decode.py) via lax.scan."""
    import jax
    import jax.numpy as jnp

    def _f(emis, trans):
        B, T, N = emis.shape

        def step(carry, e_t):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None]  # [B, from, to]
            best = jnp.max(cand, axis=1) + e_t
            idx = jnp.argmax(cand, axis=1)
            return best, idx

        score0 = emis[:, 0]
        scores, backptrs = jax.lax.scan(step, score0, jnp.swapaxes(emis[:, 1:], 0, 1))
        last_best = jnp.argmax(scores, axis=-1)  # [B]

        def backtrack(carry, ptr_t):
            cur = carry
            prev = jnp.take_along_axis(ptr_t, cur[:, None], axis=1)[:, 0]
            return prev, cur

        _, path = jax.lax.scan(backtrack, last_best, backptrs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path, 0, 1),
                                last_best[:, None]], axis=1)
        return jnp.max(scores, axis=-1), path
    return apply_op(_f, potentials, transition_params)


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
