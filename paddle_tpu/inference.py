"""paddle_tpu.inference — reference python/paddle/inference (Predictor over a
saved inference program, paddle/fluid/inference/api/paddle_inference_api.h).

TPU-native: the saved program is a jax.export artifact (serialized
StableHLO with calling convention); Predictor deserializes and executes it
directly — no Python Layer rebuild.  Config.set_model(layer) remains the
eager path for models constructed in-process.
"""
import numpy as np

import jax

from .framework.core import Tensor
from .nn.layer_base import Layer, buffer_pytree, functional_call, state_pytree

__all__ = ["Config", "Predictor", "create_predictor", "DataType",
           "PlaceType", "PrecisionType", "PredictorPool", "get_version",
           "get_trt_compile_version", "get_trt_runtime_version",
           "get_num_bytes_of_data_type", "convert_to_mixed_precision"]


class DataType:
    """reference paddle_infer.DataType enum."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    NPU = 3
    TPU = 4           # the device this framework actually targets
    CUSTOM = 5


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    return _DTYPE_BYTES[dtype]


def get_version():
    import jax
    return f"paddle_tpu inference (jax {jax.__version__}, XLA runtime)"


def get_trt_compile_version():
    return (0, 0, 0)    # no TensorRT on TPU — XLA is the compiler


def get_trt_runtime_version():
    return (0, 0, 0)


def convert_to_mixed_precision(*a, **k):
    raise NotImplementedError(
        "use paddle_tpu.amp.auto_cast / model.bfloat16(): on TPU mixed "
        "precision is a trace-time dtype decision, not a program rewrite")


class PredictorPool:
    """reference paddle_infer.PredictorPool: N predictor handles sharing
    ONE compiled program and params (XLA executables are thread-safe —
    building N independent Predictors would compile and host the same
    model N times)."""

    def __init__(self, config, size=1):
        import copy
        base = Predictor(config)
        self._predictors = [base]
        for _ in range(size - 1):
            self._predictors.append(copy.copy(base))  # shares _fn/_params

    def retrive(self, idx):            # reference spelling
        return self._predictors[idx]

    retrieve = retrive


class Config:
    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model = None
        self._ir_optim = True
        self._memory_optim = False

    def set_model(self, layer: Layer):
        self._model = layer
        return self

    # Device knobs kept for API parity (this framework targets TPU; XLA
    # owns device placement)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        """Reference: toggle the IR optimization passes. TPU mapping:
        ir_optim ON = the whole forward is one jit-compiled XLA program
        (fused, scheduled); OFF = eager op-by-op execution — genuinely
        unoptimized, for debugging numerics op-at-a-time."""
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        """Reference: reuse variable memory across ops. TPU mapping:
        donate the INPUT buffers to the compiled program
        (donate_argnums), letting XLA reuse their HBM for activations/
        outputs instead of holding inputs live across the run."""
        self._memory_optim = bool(x)

    def memory_optim_enabled(self):
        return self._memory_optim


class Predictor:
    def __init__(self, config: Config):
        self._translated = None
        if config._model is None and config.prog_file:
            from . import jit as pjit
            path = config.prog_file
            for suffix in (".pdmodel", ".jaxprog"):
                if path.endswith(suffix):
                    path = path[: -len(suffix)]
            loaded = pjit.load(path)
            if not loaded.runnable:
                why = getattr(loaded, "_load_error", None)
                why = (f"its program failed to deserialize ({why})"
                       if why else "it was saved without input_spec "
                       "(no executable program)")
                raise RuntimeError(
                    f"{config.prog_file!r} holds weights but {why}; "
                    "re-save with jit.save(layer, path, input_spec=[...]) "
                    "or use Config.set_model(layer)")
            self._translated = loaded
            self.model = loaded
            return
        self.model = config._model
        self.model.eval()
        params = state_pytree(self.model)
        params.update(buffer_pytree(self.model))
        self._params = params
        self._jitted = config._ir_optim

        def pure(params, args):
            with functional_call(self.model, params):
                out = self.model(*[Tensor(a) for a in args])
            return out._value if isinstance(out, Tensor) else out
        self._donate_inputs = config._ir_optim and config._memory_optim
        if config._ir_optim:
            # memory_optim donates the (per-call) input pytree so XLA
            # reuses its HBM for activations; params stay live across runs
            self._fn = jax.jit(
                pure, donate_argnums=(1,) if config._memory_optim else ())
        else:
            self._fn = pure          # eager: no XLA program, op-by-op

    def run(self, inputs):
        arrs = [i._value if isinstance(i, Tensor) else np.asarray(i)
                for i in inputs]
        if self._translated is not None:
            out = self._translated(*arrs)
            return list(out) if isinstance(out, (list, tuple)) else [out]
        if getattr(self, "_donate_inputs", False):
            # donation destroys the buffer: a caller-owned jax array
            # (paddle Tensor input) must be copied, or their tensor dies
            args = tuple(jax.numpy.array(a, copy=True)
                         if isinstance(a, jax.Array)
                         else jax.numpy.asarray(a) for a in arrs)
        else:
            args = tuple(jax.numpy.asarray(a) for a in arrs)
        out = self._fn(self._params, args)
        return [Tensor(out)] if not isinstance(out, (list, tuple)) \
            else [Tensor(o) for o in out]


def create_predictor(config: Config):
    return Predictor(config)


class _ContribUtils:
    """Reference inference/contrib/utils: copy_tensor(dst, src)."""

    @staticmethod
    def copy_tensor(dst, src):
        from .framework.core import Tensor
        v = src._value if isinstance(src, Tensor) else src
        dst.set_value(np.asarray(v))
        return dst


class _Contrib:
    utils = _ContribUtils()


contrib = _Contrib()
