"""paddle_tpu.inference — reference python/paddle/inference (Predictor over a
saved inference program). TPU-native: a Predictor wraps a jit-compiled
functional model loaded via paddle_tpu.jit artifacts + weights."""
import numpy as np

import jax

from .framework.core import Tensor
from .nn.layer_base import Layer, buffer_pytree, functional_call, state_pytree

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model = None

    def set_model(self, layer: Layer):
        self._model = layer
        return self

    # GPU/IR knobs kept for API parity (XLA handles all of it)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self):
        pass


class Predictor:
    def __init__(self, config: Config):
        if config._model is None and config.prog_file:
            from . import jit as pjit
            loaded = pjit.load(config.prog_file.replace(".pdmodel", ""))
            raise NotImplementedError(
                "rebuild the python Layer and use Config.set_model(layer) with "
                "weights from jit.load — direct program execution needs a "
                "StableHLO runtime binding (planned)")
        self.model = config._model
        self.model.eval()
        params = state_pytree(self.model)
        params.update(buffer_pytree(self.model))
        self._params = params

        def pure(params, *args):
            with functional_call(self.model, params):
                out = self.model(*[Tensor(a) for a in args])
            return out._value if isinstance(out, Tensor) else out
        self._fn = jax.jit(pure)

    def run(self, inputs):
        arrs = [i._value if isinstance(i, Tensor) else np.asarray(i) for i in inputs]
        out = self._fn(self._params, *arrs)
        return [Tensor(out)] if not isinstance(out, (list, tuple)) else [Tensor(o) for o in out]


def create_predictor(config: Config):
    return Predictor(config)
