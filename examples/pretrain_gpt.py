"""GPT pretraining recipe — the paddle_tpu rendering of the reference's
PaddleNLP gpt-3 + fleet run scripts.

Usage (synthetic data):
    python examples/pretrain_gpt.py --config gpt_125m --steps 50
With a token file (flat int32 binary):
    python examples/pretrain_gpt.py --data tokens.bin --config gpt_1p3b
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt_125m")
    ap.add_argument("--data", default=None, help="flat int32 token file")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup", type=int, default=100)
    ap.add_argument("--dp", type=int, default=0, help="0 = all devices")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import LossBuffer, Trainer
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.io import DeviceLoader
    from paddle_tpu.models import GPT, GPTPretrainingCriterion
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.runtime import TokenLoader

    n_dev = len(jax.devices())
    dp = args.dp or n_dev // (args.tp * args.fsdp)
    build_mesh(dp=dp, tp=args.tp, fsdp=args.fsdp)

    cfg = getattr(gpt_mod, args.config)(max_seq_len=args.seq)
    paddle.seed(0)
    model = GPT(cfg)
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    sched = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.CosineAnnealingDecay(args.lr, args.steps),
        args.warmup, 0.0, args.lr)
    opt = paddle.optimizer.AdamW(
        learning_rate=sched, weight_decay=0.1,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
        accumulator_dtype="bfloat16")

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]))
        return crit(logits, paddle.to_tensor(batch["labels"]))

    trainer = Trainer(model, opt, loss_fn)
    mgr = CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None

    if args.data:
        loader = TokenLoader(args.data, args.batch, args.seq)
        def batches():
            for window in loader:
                yield {"input_ids": window[:, :-1], "labels": window[:, 1:]}
    else:
        rng = np.random.RandomState(0)
        def batches():
            while True:
                ids = rng.randint(0, cfg.vocab_size, (args.batch, args.seq + 1))
                yield {"input_ids": ids[:, :-1].astype("int32"),
                       "labels": ids[:, 1:].astype("int32")}

    # async input pipeline: token assembly + sharded H2D copy run in a
    # background thread, two batches ahead of the compiled step; losses
    # stay on-device and sync once per log window (LossBuffer)
    loader = DeviceLoader(batches(), depth=2)
    losses = LossBuffer(drain_every=10)
    t0 = time.time()
    for step, batch in enumerate(loader):
        if step >= args.steps:
            break
        losses.append(trainer.step(batch))
        if step % 10 == 0:
            dt = time.time() - t0
            tok_s = args.batch * args.seq * (step + 1) / max(dt, 1e-9)
            print(f"step {step}: loss={losses.drain():.4f} "
                  f"({tok_s:.0f} tok/s, lr={opt.get_lr():.2e})")
        if mgr and step and step % 100 == 0:
            losses.drain()          # sync before touching host state
            trainer.sync_to_model()
            mgr.save(step, {"model": model.state_dict(),
                            "opt": opt.state_dict(), "step": step})
    losses.drain()
    loader.close()
    print(f"done: {args.steps} steps in {time.time()-t0:.1f}s "
          f"(input pipeline: {loader.stats.snapshot()})")


if __name__ == "__main__":
    main()
