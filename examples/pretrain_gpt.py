"""GPT pretraining recipe — the paddle_tpu rendering of the reference's
PaddleNLP gpt-3 + fleet run scripts.

Usage (synthetic data):
    python examples/pretrain_gpt.py --config gpt_125m --steps 50
With a token file (flat int32 binary):
    python examples/pretrain_gpt.py --data tokens.bin --config gpt_1p3b
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt_125m")
    ap.add_argument("--data", default=None, help="flat int32 token file")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup", type=int, default=100)
    ap.add_argument("--dp", type=int, default=0, help="0 = all devices")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--multi-step", type=int, default=0,
                    help="fuse N train steps into one compiled scan "
                         "(1 = per-step loop; 0 = auto: price N from the "
                         "roofline step time vs the measured host sync "
                         "cost, cost_model.train_horizon)")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import LossBuffer, Trainer
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.io import DeviceLoader
    from paddle_tpu.models import GPT, GPTPretrainingCriterion
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.runtime import TokenLoader

    n_dev = len(jax.devices())
    dp = args.dp or n_dev // (args.tp * args.fsdp)
    build_mesh(dp=dp, tp=args.tp, fsdp=args.fsdp)

    cfg = getattr(gpt_mod, args.config)(max_seq_len=args.seq)
    paddle.seed(0)
    model = GPT(cfg)
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    sched = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.CosineAnnealingDecay(args.lr, args.steps),
        args.warmup, 0.0, args.lr)
    opt = paddle.optimizer.AdamW(
        learning_rate=sched, weight_decay=0.1,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
        accumulator_dtype="bfloat16")

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]))
        return crit(logits, paddle.to_tensor(batch["labels"]))

    trainer = Trainer(model, opt, loss_fn)
    mgr = CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None

    if args.data:
        # NOT named `loader`: the DeviceLoader below rebinds that name
        # before this lazy generator first runs, and the closure would
        # then feed the DeviceLoader on itself (generator reentrancy)
        token_loader = TokenLoader(args.data, args.batch, args.seq)
        def batches():
            for window in token_loader:
                yield {"input_ids": window[:, :-1], "labels": window[:, 1:]}
    else:
        rng = np.random.RandomState(0)
        def batches():
            while True:
                ids = rng.randint(0, cfg.vocab_size, (args.batch, args.seq + 1))
                yield {"input_ids": ids[:, :-1].astype("int32"),
                       "labels": ids[:, 1:].astype("int32")}

    # multi-step horizon: N fused steps per compiled dispatch, host
    # contact only at horizon boundaries. --multi-step 0 prices N the
    # decode_horizon way: roofline step time (analytic FLOPs of the REAL
    # traced step vs the chip) against the measured host sync cost.
    n_multi = args.multi_step
    if n_multi <= 0:
        from paddle_tpu.cost_model import (jaxpr_flops, roofline_step_time,
                                           train_horizon)
        probe = {"input_ids": np.zeros((args.batch, args.seq), np.int32),
                 "labels": np.zeros((args.batch, args.seq), np.int32)}
        flops = jaxpr_flops(trainer.analysis_program(probe).jaxpr)
        # HBM leg: f32 params + Adam m/v, each read AND written once
        # per step: 3 tensors x 4 bytes x 2 directions = 24 bytes/param
        hbm = 24 * cfg.num_params()
        step_s = roofline_step_time(flops, hbm).step_s
        n_multi = train_horizon(step_s)
        print(f"train_horizon: roofline step {step_s*1e3:.2f} ms -> N={n_multi}")
    n_multi = max(1, min(int(n_multi), args.steps))

    # async input pipeline: token assembly + sharded H2D copy run in a
    # background thread, two batches ahead of the compiled step; with
    # N>1 the loader stacks N batches per horizon in the worker thread.
    # Losses stay on-device and sync once per log window / horizon
    # boundary (LossBuffer accepts the [N] horizon vectors).
    loader = DeviceLoader(batches(), depth=2)
    losses = LossBuffer(drain_every=max(10, n_multi))
    t0 = time.time()
    feed = iter(loader) if n_multi == 1 else loader.stack(n_multi)
    step = 0
    log_every = 10 if n_multi == 1 else n_multi * ((10 + n_multi - 1)
                                                   // n_multi)
    import jax
    for item in feed:
        if step >= args.steps:
            break
        if n_multi == 1:
            losses.append(trainer.step(item))
            step += 1
        else:
            # a finite --data source can yield a final stack m < n deep
            m = jax.tree_util.tree_leaves(item)[0].shape[0]
            if m == n_multi and step + n_multi <= args.steps:
                losses.append(trainer.step_multi(item))
                step += n_multi
            else:
                # partial final horizon (short stack OR --steps
                # boundary): per-step fallback over slices of the
                # stacked feed (no fresh m-step scan compile)
                for i in range(min(m, args.steps - step)):
                    losses.append(trainer.step(
                        jax.tree_util.tree_map(lambda v: v[i], item)))
                    step += 1
        if step % log_every == 0 or step >= args.steps:
            dt = time.time() - t0
            tok_s = args.batch * args.seq * step / max(dt, 1e-9)
            print(f"step {step}: loss={losses.drain():.4f} "
                  f"({tok_s:.0f} tok/s, {step/max(dt,1e-9):.1f} steps/s, "
                  f"lr={opt.get_lr():.2e})")
        # checkpoint ticks land on horizon boundaries by construction
        # (this loop only sees whole horizons)
        if mgr and step and step % 100 < n_multi and step >= 100:
            losses.drain()          # sync before touching host state
            trainer.sync_to_model()
            mgr.save(step, {"model": model.state_dict(),
                            "opt": opt.state_dict(), "step": step})
    losses.drain()
    if hasattr(feed, "close"):
        feed.close()
    loader.close()
    dt = time.time() - t0
    syncs_per_step = losses.fetches / max(step, 1)
    print(f"done: {step} steps in {dt:.1f}s = "
          f"{step/max(dt,1e-9):.2f} train_steps_per_sec "
          f"(multi_step N={n_multi}, {syncs_per_step:.3f} host syncs/step; "
          f"input pipeline: {loader.stats.snapshot()})")


if __name__ == "__main__":
    main()
