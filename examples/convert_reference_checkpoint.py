"""Convert a reference PaddlePaddle checkpoint and run it on TPU.

Usage (with a checkpoint downloaded from the paddle model zoo, e.g.
resnet50.pdparams from the URLs in the reference's
python/paddle/vision/models/resnet.py):

    python examples/convert_reference_checkpoint.py resnet50.pdparams

What happens:
  1. `load_reference_state_dict` unpickles the reference paddle.save file
     tolerantly — paddle-2.1 (name, ndarray) tuples and pickled
     framework-internal classes (EagerParamBase, ...) are both handled
     without the reference runtime installed.
  2. `apply_reference_checkpoint` pushes it into the matching paddle_tpu
     model (state-dict names are reference-compatible: dotted sublayer
     paths, BatchNorm `_mean`/`_variance`, Linear `[in, out]` weights).
  3. The model runs inference / can be jit.save'd for the Predictor.

tests/test_checkpoint_convert_e2e.py runs this flow on a full ResNet-50
state dict (synthesized in the reference on-disk format — the CI
environment has no network for a zoo download).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle


def main(path):
    sd = paddle.utils.load_reference_state_dict(path)
    print(f"loaded {len(sd)} tensors from {path}")
    n_cls = sd.get("fc.weight", np.zeros((1, 1000))).shape[-1]
    model = paddle.vision.models.resnet50(num_classes=n_cls)
    missing, unexpected = paddle.utils.apply_reference_checkpoint(
        model, path, strict=False)
    print(f"applied: {len(missing)} missing, {len(unexpected)} unexpected")
    model.eval()
    x = paddle.to_tensor(np.zeros((1, 3, 224, 224), "float32"))
    out = model(x)
    print("logits:", np.asarray(out._value)[0, :5])


if __name__ == "__main__":
    main(sys.argv[1])
