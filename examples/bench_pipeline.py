"""Microbenchmark: 1F1B vs GPipe pipeline schedules on the virtual CPU mesh.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
          python examples/bench_pipeline.py

Measures wall-clock per training step and compiled temp (activation) memory
for GPTStacked at pp=4 x dp=2, 8 microbatches. Representative result
(this machine, 2026-07):

    gpipe            : 16.7 s/step, temp=304.5 MB
    1f1b             :  9.6 s/step, temp= 53.5 MB  -> 1.75x faster, 5.7x less
    interleaved      :  8.3 s/step, temp=313.6 MB  (autodiff backward)
    interleaved_1f1b :  7.0 s/step, temp= 38.0 MB  -> 1.19x faster, 8.3x less
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.trainer import Trainer
from paddle_tpu.models import GPTConfig, GPTPretrainingCriterion, GPTStacked


def main():
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=8, num_heads=8,
                    max_seq_len=128, dtype="float32", remat=False)
    crit = GPTPretrainingCriterion()

    def loss_fn(m, b):
        return crit(m(paddle.to_tensor(b["input_ids"])),
                    paddle.to_tensor(b["labels"]))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (16, 129))
    batch = {"input_ids": ids[:, :-1].astype("int32"),
             "labels": ids[:, 1:].astype("int32")}

    results = {}
    for sched in ("gpipe", "1f1b", "interleaved", "interleaved_1f1b"):
        paddle.seed(0)
        build_mesh(pp=4, dp=2)
        model = GPTStacked(cfg, pp_microbatches=8, pp_schedule=sched)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3)
        trainer = Trainer(model, opt, loss_fn)
        loss = trainer.step(batch)  # compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(5):
            loss = trainer.step(batch)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / 5
        lowered = trainer.lower_step(batch, 1e-3)
        ma = lowered.compile().memory_analysis()
        temp = getattr(ma, "temp_size_in_bytes", 0)
        results[sched] = (dt, temp)
        print(f"{sched}: {dt:.2f} s/step, temp={temp / 1e6:.1f} MB, "
              f"loss={float(loss):.4f}")

    g, f = results["gpipe"], results["1f1b"]
    print(f"1f1b speedup: {g[0] / f[0]:.2f}x, temp reduction: {g[1] / f[1]:.1f}x")
    i, i1 = results["interleaved"], results["interleaved_1f1b"]
    print(f"interleaved_1f1b vs interleaved (autodiff): "
          f"{i[0] / i1[0]:.2f}x faster, {i[1] / i1[1]:.1f}x less temp")


if __name__ == "__main__":
    main()
