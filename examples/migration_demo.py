"""Migration proof: a PaddlePaddle v2.3-style training script, written
exactly as the reference docs teach it — high-level Model.fit with amp,
LR schedule, metrics, checkpointing, dynamic-to-static export, and an
inference reload — that runs on paddle_tpu with ONLY the import line
changed (`import paddle` -> `import paddle_tpu as paddle`).

    JAX_PLATFORMS=cpu python examples/migration_demo.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle  # the ONE changed line (was: import paddle)
from paddle_tpu import nn
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.static import InputSpec


class RandomDigits(Dataset):
    """Stands in for paddle.vision.datasets.MNIST (zero-egress box):
    each class paints a distinct 7x7 patch bright, so the net can
    actually learn and evaluate() has a meaningful accuracy."""

    def __init__(self, n=256, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 10, (n, 1)).astype("int64")
        self.x = rng.randn(n, 1, 28, 28).astype("float32") * 0.3
        for i, cls in enumerate(self.y[:, 0]):
            r, c = divmod(int(cls), 4)
            self.x[i, 0, r * 7:(r + 1) * 7, c * 7:(c + 1) * 7] += 2.0

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def main():
    paddle.seed(42)

    # --- the reference LeNet quickstart, verbatim style ----------------
    net = nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(400, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))

    model = paddle.Model(net)
    scheduler = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=1e-3, T_max=10)
    opt = paddle.optimizer.AdamW(learning_rate=scheduler,
                                 weight_decay=0.01,
                                 parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy())

    loader = DataLoader(RandomDigits(), batch_size=32, shuffle=True)
    model.fit(loader, epochs=4, verbose=0)
    eval_res = model.evaluate(loader, verbose=0)
    acc = float(eval_res["acc"])
    print(f"fit done: eval loss={eval_res['loss']:.3f} acc={acc:.3f}")
    assert acc > 0.5, f"LeNet failed to learn the patch task: {acc}"

    # --- checkpoint round trip (reference save/load) -------------------
    paddle.save(net.state_dict(), "/tmp/migration_demo.pdparams")
    net2 = nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(400, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))
    net2.set_state_dict(paddle.load("/tmp/migration_demo.pdparams"))
    x = paddle.to_tensor(np.zeros((2, 1, 28, 28), "float32"))
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)
    print("checkpoint round-trips")

    # --- amp fine-tune step (reference GradScaler recipe) --------------
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024)
    with paddle.amp.auto_cast():
        loss = paddle.nn.functional.cross_entropy(
            net(x), paddle.to_tensor(np.array([[1], [7]], "int64")))
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()
    print(f"amp step: loss={float(loss.numpy()):.4f}")

    # --- dynamic-to-static export + inference reload -------------------
    paddle.jit.save(net, "/tmp/migration_demo_infer",
                    input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    loaded = paddle.jit.load("/tmp/migration_demo_infer")
    np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                               rtol=1e-4, atol=1e-5)
    print("jit.save/load round-trips — migration demo complete")


if __name__ == "__main__":
    main()
