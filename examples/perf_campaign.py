"""TPU perf campaign: structured sweeps for the BENCH configs that sit
below the 0.35-MFU north star (ResNet-50, BERT-base), plus GPT
confirmation.  Run ON THE CHIP; each trial prints one JSON line and
appends to perf_campaign_results.jsonl so partial runs still record.

    python examples/perf_campaign.py resnet   # bs + BN-dtype sweep
    python examples/perf_campaign.py bert     # bs + dropout + tile sweep
    python examples/perf_campaign.py gpt      # remat/bs confirmation
    python examples/perf_campaign.py hlo      # fusion audit (transpose/f32 counts)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def record(trial):
    line = json.dumps(trial)
    print(line, flush=True)
    with open("perf_campaign_results.jsonl", "a") as f:
        f.write(line + "\n")


def banked(_defaults=None, **keys):
    """True if a successful trial matching every key=value is already in
    the results file — lets a retried stage skip straight to the trials a
    wedge cut short instead of re-spending tunnel minutes.  `_defaults`
    supplies values for keys older rows never recorded (e.g. pre-r5 gpt
    rows carry no `accum`: matching accum=1 against them is correct,
    while an accum=2 row must NOT satisfy an accum=1 query)."""
    defaults = _defaults or {}
    try:
        with open("perf_campaign_results.jsonl") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "error" in row:
                    continue
                if all(row.get(k, defaults.get(k)) == v
                       for k, v in keys.items()):
                    return True
    except OSError:
        pass
    return False


def _resnet_trial(batch_size, steps=10, stem_s2d=False):
    import bench
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer

    paddle.seed(0)
    build_mesh(dp=1)
    model = paddle.vision.models.resnet50(num_classes=1000,
                                          data_format="NHWC",
                                          stem_s2d=stem_s2d)
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    weight_decay=1e-4)

    def loss_fn(m, b):
        logits = m(paddle.to_tensor(b["image"]))
        return paddle.nn.functional.cross_entropy(
            logits, paddle.to_tensor(b["label"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    batch = bench._stage({
        "image": rng.randn(batch_size, 224, 224, 3).astype("float32"),
        "label": rng.randint(0, 1000, (batch_size,)).astype("int64")})
    dt = bench._measure(trainer, batch, steps, f"resnet_bs{batch_size}")
    imgs_s = batch_size / dt
    mfu = 3 * 8.2e9 * imgs_s / bench.chip_peak_flops()
    return {"config": "resnet50", "bs": batch_size, "stem_s2d": stem_s2d,
            "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)}, trainer, batch


def run_resnet():
    # sweep batch AND the space-to-depth stem rewrite (exact-equivalent
    # MXU-friendly 7x7/s2; ops/space_to_depth.py, CPU-parity tested)
    ok = 0
    for bs in (128, 256, 512):
        for s2d in (False, True):
            if banked(config="resnet50", bs=bs, stem_s2d=s2d):
                ok += 1
                continue
            try:
                trial, _, _ = _resnet_trial(bs, stem_s2d=s2d)
                record(trial)
                ok += 1
            except Exception as e:
                record({"config": "resnet50", "bs": bs, "stem_s2d": s2d,
                        "error": f"{type(e).__name__}: {str(e)[:160]}"})
                import gc
                gc.collect()
    if ok:  # all-errored sweep stays unbanked so the watch retries it
        record({"config": "resnet_stage_done"})


def run_hlo_audit():
    """Compile the ResNet step and count fusion red flags in optimized
    HLO: f32 convolutions, transposes, copies (docs/performance.md
    profiling rules)."""
    import jax.numpy as jnp
    trial, trainer, batch = _resnet_trial(128, steps=1)
    lowered = trainer.lower_step(batch, 0.1)
    txt = lowered.compile().as_text()
    counts = {
        "conv_f32": sum(1 for l in txt.splitlines()
                        if "convolution" in l and "f32[" in l.split("=")[0]),
        "conv_total": txt.count(" convolution("),
        "transpose": txt.count(" transpose("),
        "copy": txt.count(" copy("),
        "all_reduce": txt.count("all-reduce"),
        "custom_call": txt.count("custom-call"),
    }
    record({"config": "resnet50_hlo_audit", **counts})
    log("lines:", len(txt.splitlines()))


def _bert_trial(batch_size, seq_len, dropout, steps=10):
    import bench
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models.bert import (BertForPretraining,
                                        BertPretrainingCriterion, bert_base)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = bert_base(dtype="bfloat16")
    if not dropout:
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
    model = BertForPretraining(cfg)
    model.bfloat16()
    model.train()
    crit = BertPretrainingCriterion(cfg.vocab_size)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 accumulator_dtype="bfloat16")

    def loss_fn(m, b):
        mlm, nsp = m(paddle.to_tensor(b["input_ids"]),
                     attention_mask=paddle.to_tensor(b["attention_mask"]))
        return crit(mlm, nsp, paddle.to_tensor(b["mlm_labels"]),
                    paddle.to_tensor(b["nsp_labels"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, cfg.vocab_size, (batch_size, seq_len))
    labels[rng.rand(batch_size, seq_len) > 0.15] = -100
    lengths = rng.randint(int(seq_len * 0.75), seq_len + 1, (batch_size,))
    attn = (np.arange(seq_len)[None, :] < lengths[:, None])
    batch = bench._stage({
        "input_ids": rng.randint(0, cfg.vocab_size,
                                 (batch_size, seq_len)).astype("int32"),
        "attention_mask": attn.astype("int32"),
        "mlm_labels": labels.astype("int32"),
        "nsp_labels": rng.randint(0, 2, (batch_size,)).astype("int64")})
    dt = bench._measure(trainer, batch, steps,
                        f"bert_bs{batch_size}_drop{dropout}")
    seqs_s = batch_size / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = 6 * n_params * seqs_s * seq_len / bench.chip_peak_flops()
    return {"config": "bert_base", "bs": batch_size, "seq": seq_len,
            "dropout": dropout, "seqs_s": round(seqs_s, 2),
            "mfu": round(mfu, 4)}


def run_bert():
    ok = 0
    for bs, dropout in ((32, True), (32, False), (64, True), (64, False),
                        (128, True)):
        if banked(config="bert_base", bs=bs, dropout=dropout):
            ok += 1
            continue
        try:
            record(_bert_trial(bs, 512, dropout))
            ok += 1
        except Exception as e:
            record({"config": "bert_base", "bs": bs, "dropout": dropout,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    # flash-tile tune at the encoder's shape (seq 512, unmasked-causal),
    # then re-measure the strongest configs with the tuned tiles still
    # installed — tuning is per-process, so it must happen HERE, not in a
    # separate campaign stage
    try:
        from paddle_tpu.incubate.autotune import tune_flash_attention
        timings = tune_flash_attention(batch=32, seq_len=512, num_heads=12,
                                       head_dim=64, causal=False)
        best = min(timings, key=timings.get) if timings else None
        record({"config": "bert_flash_tune", "best": str(best),
                "timings_ms": {str(k): round(v * 1e3, 2)
                               for k, v in timings.items()}})
    except Exception as e:
        best = None
        record({"config": "bert_flash_tune",
                "error": f"{type(e).__name__}: {str(e)[:160]}"})
        import gc
        gc.collect()
    if best:
        # per-trial isolation like the main sweep: a failed tuned trial
        # records as bert_base (not as a tuner error) and doesn't stop
        # the other tuned batch size
        for bs in (32, 64):
            try:
                trial = _bert_trial(bs, 512, True)
                trial["tuned_tiles"] = str(best)
                record(trial)
                ok += 1
            except Exception as e:
                record({"config": "bert_base", "bs": bs, "dropout": True,
                        "tuned_tiles": str(best),
                        "error": f"{type(e).__name__}: {str(e)[:160]}"})
                import gc
                gc.collect()
    if ok:
        record({"config": "bert_stage_done"})


def run_flash_tune():
    """On-device flash tile autotune at BERT's shape (seq 512, masked)."""
    from paddle_tpu.incubate.autotune import tune_flash_attention
    best = tune_flash_attention(batch=32, seq_len=512, num_heads=12,
                                head_dim=64, causal=False)
    record({"config": "flash_tune_bert", "best": str(best)})


def run_yolo():
    """First-ever on-chip YOLOv3-DarkNet53 numbers (BASELINE config 4).
    bs sweep at 320; one 416 trial for the reference's headline shape."""
    import bench
    ok = 0
    for bs, size in ((16, 320), (32, 320), (16, 416)):
        if banked(config="yolov3", bs=bs, size=size):
            ok += 1
            continue
        try:
            imgs_s, mfu = bench.run_yolov3(batch_size=bs, size=size)
            record({"config": "yolov3", "bs": bs, "size": size,
                    "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "yolov3", "bs": bs, "size": size,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "yolo_stage_done"})


def run_ocr():
    """The OCR half of BASELINE config 4: CRNN recognition at PP-OCR's
    32xW crop shape.  Own stage marker — a wedge after the yolo sweep
    must not bank this stage as done."""
    import bench
    ok = 0
    for bs in (64, 128):
        if banked(config="crnn", bs=bs):
            ok += 1
            continue
        try:
            imgs_s, mfu = bench.run_crnn(batch_size=bs)
            record({"config": "crnn", "bs": bs,
                    "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "crnn", "bs": bs,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "ocr_stage_done"})


def run_moe():
    """First-ever on-chip GPT-MoE numbers (BASELINE config 5): bs sweep
    on the default top-k gate, plus one gshard trial."""
    import bench
    ok = 0
    for bs, gate in ((8, "topk"), (16, "topk"), (8, "gshard")):
        if banked(config="gpt_moe", bs=bs, gate=gate):
            ok += 1
            continue
        try:
            tok_s, mfu = bench.run_gpt_moe(batch_size=bs, gate=gate)
            record({"config": "gpt_moe", "bs": bs, "gate": gate,
                    "tok_s": round(tok_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "gpt_moe", "bs": bs, "gate": gate,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "moe_stage_done"})


def run_decode():
    """On-chip serving numbers: decode tok/s vs HBM roofline for bf16 /
    a8w8 / w4a16, plus the speculative wall-clock ceiling (both were
    CPU-only until the tunnel returned)."""
    import bench
    ok = 0
    for quant in (None, "a8w8", "w4a16"):
        if banked(config="decode", quant=quant or "bf16"):
            ok += 1
            continue
        try:
            r = bench.run_decode(quant=quant)
            record({"config": "decode", "quant": quant or "bf16", **r})
            ok += 1
        except Exception as e:
            record({"config": "decode", "quant": quant or "bf16",
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    try:
        record({"config": "speculative", **bench.run_speculative()})
        ok += 1
    except Exception as e:
        record({"config": "speculative",
                "error": f"{type(e).__name__}: {str(e)[:160]}"})
    if ok:
        record({"config": "decode_stage_done"})


def run_gpt():
    import bench
    ok = 0
    # bs7/dots probes the last step before the bs8/dots compile cliff;
    # bs8/dots/accum2 gets effective batch 8 at microbatch-4 peak memory
    # (gradient-merge scan), sidestepping that cliff entirely
    # bs6/accum2 amortizes the optimizer+grad-clip epilogue over an
    # effective batch of 12 at bs6's proven-safe peak memory — the
    # cheapest shot past 0.641 before the quarantined bs8 trials
    for name, bs, rp, accum in (
            ("gpt_1p3b", 4, "dots", 1), ("gpt_1p3b", 6, "dots", 1),
            ("gpt_1p3b", 6, "dots", 2), ("gpt_1p3b", 7, "dots", 1),
            ("gpt_1p3b", 8, "dots", 2), ("gpt_1p3b", 8, "full", 1)):
        if banked(config=name, bs=bs, remat=rp, accum=accum,
                  _defaults={"accum": 1}):
            ok += 1
            continue
        try:
            tok_s, mfu, _, static_hbm = bench.run_config(
                name, bs, 1024, remat_policy=rp, grad_accum=accum)
            record({"config": name, "bs": bs, "remat": rp, "accum": accum,
                    "tok_s": round(tok_s, 1), "mfu": round(mfu, 4),
                    "static_peak_hbm": static_hbm})
            ok += 1
        except Exception as e:
            record({"config": name, "bs": bs, "remat": rp, "accum": accum,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "gpt_stage_done"})


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("resnet", "all"):
        run_resnet()
    if which in ("hlo",):
        run_hlo_audit()
    if which in ("bert", "all"):
        run_bert()
    if which in ("tune",):
        run_flash_tune()
    if which in ("yolo", "all"):
        run_yolo()
    if which in ("ocr", "crnn", "all"):
        run_ocr()
    if which in ("moe", "all"):
        run_moe()
    if which in ("gpt", "all"):
        run_gpt()
    if which in ("decode", "all"):
        run_decode()


if __name__ == "__main__":
    main()
