"""TPU perf campaign: structured sweeps for the BENCH configs that sit
below the 0.35-MFU north star (ResNet-50, BERT-base), plus GPT
confirmation.  Run ON THE CHIP; each trial prints one JSON line and
appends to perf_campaign_results.jsonl so partial runs still record.

    python examples/perf_campaign.py resnet   # bs + BN-dtype sweep
    python examples/perf_campaign.py bert     # bs + dropout + tile sweep
    python examples/perf_campaign.py gpt      # advisor's top-2 remat/bs picks
    python examples/perf_campaign.py gpt --exhaustive   # full grid
    python examples/perf_campaign.py hlo      # fusion audit (transpose/f32 counts)

The gpt stage consults the static remat/microbatch advisor
(paddle_tpu.analysis.autotune) and measures only its top-2 candidates
unless --exhaustive is given — the advisor ranks the whole grid from
CPU-side traces, so a 6-point sweep costs 2 on-chip trials.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def record(trial):
    line = json.dumps(trial)
    print(line, flush=True)
    with open("perf_campaign_results.jsonl", "a") as f:
        f.write(line + "\n")


def banked(_defaults=None, **keys):
    """True if a successful trial matching every key=value is already in
    the results file — lets a retried stage skip straight to the trials a
    wedge cut short instead of re-spending tunnel minutes.  `_defaults`
    supplies values for keys older rows never recorded (e.g. pre-r5 gpt
    rows carry no `accum`: matching accum=1 against them is correct,
    while an accum=2 row must NOT satisfy an accum=1 query)."""
    defaults = _defaults or {}
    try:
        with open("perf_campaign_results.jsonl") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "error" in row:
                    continue
                if all(row.get(k, defaults.get(k)) == v
                       for k, v in keys.items()):
                    return True
    except OSError:
        pass
    return False


def _resnet_trial(batch_size, steps=10, stem_s2d=False):
    import bench
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer

    paddle.seed(0)
    build_mesh(dp=1)
    model = paddle.vision.models.resnet50(num_classes=1000,
                                          data_format="NHWC",
                                          stem_s2d=stem_s2d)
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    weight_decay=1e-4)

    def loss_fn(m, b):
        logits = m(paddle.to_tensor(b["image"]))
        return paddle.nn.functional.cross_entropy(
            logits, paddle.to_tensor(b["label"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    batch = bench._stage({
        "image": rng.randn(batch_size, 224, 224, 3).astype("float32"),
        "label": rng.randint(0, 1000, (batch_size,)).astype("int64")})
    dt = bench._measure(trainer, batch, steps, f"resnet_bs{batch_size}")
    imgs_s = batch_size / dt
    mfu = 3 * 8.2e9 * imgs_s / bench.chip_peak_flops()
    return {"config": "resnet50", "bs": batch_size, "stem_s2d": stem_s2d,
            "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)}, trainer, batch


def run_resnet():
    # sweep batch AND the space-to-depth stem rewrite (exact-equivalent
    # MXU-friendly 7x7/s2; ops/space_to_depth.py, CPU-parity tested)
    ok = 0
    for bs in (128, 256, 512):
        for s2d in (False, True):
            if banked(config="resnet50", bs=bs, stem_s2d=s2d):
                ok += 1
                continue
            try:
                trial, _, _ = _resnet_trial(bs, stem_s2d=s2d)
                record(trial)
                ok += 1
            except Exception as e:
                record({"config": "resnet50", "bs": bs, "stem_s2d": s2d,
                        "error": f"{type(e).__name__}: {str(e)[:160]}"})
                import gc
                gc.collect()
    if ok:  # all-errored sweep stays unbanked so the watch retries it
        record({"config": "resnet_stage_done"})


def run_hlo_audit():
    """Compile the ResNet step and count fusion red flags in optimized
    HLO: f32 convolutions, transposes, copies (docs/performance.md
    profiling rules)."""
    import jax.numpy as jnp
    trial, trainer, batch = _resnet_trial(128, steps=1)
    lowered = trainer.lower_step(batch, 0.1)
    txt = lowered.compile().as_text()
    counts = {
        "conv_f32": sum(1 for l in txt.splitlines()
                        if "convolution" in l and "f32[" in l.split("=")[0]),
        "conv_total": txt.count(" convolution("),
        "transpose": txt.count(" transpose("),
        "copy": txt.count(" copy("),
        "all_reduce": txt.count("all-reduce"),
        "custom_call": txt.count("custom-call"),
    }
    record({"config": "resnet50_hlo_audit", **counts})
    log("lines:", len(txt.splitlines()))


def _bert_trial(batch_size, seq_len, dropout, steps=10):
    import bench
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models.bert import (BertForPretraining,
                                        BertPretrainingCriterion, bert_base)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = bert_base(dtype="bfloat16")
    if not dropout:
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
    model = BertForPretraining(cfg)
    model.bfloat16()
    model.train()
    crit = BertPretrainingCriterion(cfg.vocab_size)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 accumulator_dtype="bfloat16")

    def loss_fn(m, b):
        mlm, nsp = m(paddle.to_tensor(b["input_ids"]),
                     attention_mask=paddle.to_tensor(b["attention_mask"]))
        return crit(mlm, nsp, paddle.to_tensor(b["mlm_labels"]),
                    paddle.to_tensor(b["nsp_labels"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, cfg.vocab_size, (batch_size, seq_len))
    labels[rng.rand(batch_size, seq_len) > 0.15] = -100
    lengths = rng.randint(int(seq_len * 0.75), seq_len + 1, (batch_size,))
    attn = (np.arange(seq_len)[None, :] < lengths[:, None])
    batch = bench._stage({
        "input_ids": rng.randint(0, cfg.vocab_size,
                                 (batch_size, seq_len)).astype("int32"),
        "attention_mask": attn.astype("int32"),
        "mlm_labels": labels.astype("int32"),
        "nsp_labels": rng.randint(0, 2, (batch_size,)).astype("int64")})
    dt = bench._measure(trainer, batch, steps,
                        f"bert_bs{batch_size}_drop{dropout}")
    seqs_s = batch_size / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = 6 * n_params * seqs_s * seq_len / bench.chip_peak_flops()
    return {"config": "bert_base", "bs": batch_size, "seq": seq_len,
            "dropout": dropout, "seqs_s": round(seqs_s, 2),
            "mfu": round(mfu, 4)}


def run_bert():
    ok = 0
    for bs, dropout in ((32, True), (32, False), (64, True), (64, False),
                        (128, True)):
        if banked(config="bert_base", bs=bs, dropout=dropout):
            ok += 1
            continue
        try:
            record(_bert_trial(bs, 512, dropout))
            ok += 1
        except Exception as e:
            record({"config": "bert_base", "bs": bs, "dropout": dropout,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    # flash-tile tune at the encoder's shape (seq 512, unmasked-causal),
    # then re-measure the strongest configs with the tuned tiles still
    # installed — tuning is per-process, so it must happen HERE, not in a
    # separate campaign stage
    try:
        from paddle_tpu.incubate.autotune import tune_flash_attention
        timings = tune_flash_attention(batch=32, seq_len=512, num_heads=12,
                                       head_dim=64, causal=False)
        best = min(timings, key=timings.get) if timings else None
        record({"config": "bert_flash_tune", "best": str(best),
                "timings_ms": {str(k): round(v * 1e3, 2)
                               for k, v in timings.items()}})
    except Exception as e:
        best = None
        record({"config": "bert_flash_tune",
                "error": f"{type(e).__name__}: {str(e)[:160]}"})
        import gc
        gc.collect()
    if best:
        # per-trial isolation like the main sweep: a failed tuned trial
        # records as bert_base (not as a tuner error) and doesn't stop
        # the other tuned batch size
        for bs in (32, 64):
            try:
                trial = _bert_trial(bs, 512, True)
                trial["tuned_tiles"] = str(best)
                record(trial)
                ok += 1
            except Exception as e:
                record({"config": "bert_base", "bs": bs, "dropout": True,
                        "tuned_tiles": str(best),
                        "error": f"{type(e).__name__}: {str(e)[:160]}"})
                import gc
                gc.collect()
    if ok:
        record({"config": "bert_stage_done"})


def run_flash_tune():
    """On-device flash tile autotune at BERT's shape (seq 512, masked)."""
    from paddle_tpu.incubate.autotune import tune_flash_attention
    best = tune_flash_attention(batch=32, seq_len=512, num_heads=12,
                                head_dim=64, causal=False)
    record({"config": "flash_tune_bert", "best": str(best)})


def run_yolo():
    """First-ever on-chip YOLOv3-DarkNet53 numbers (BASELINE config 4).
    bs sweep at 320; one 416 trial for the reference's headline shape."""
    import bench
    ok = 0
    for bs, size in ((16, 320), (32, 320), (16, 416)):
        if banked(config="yolov3", bs=bs, size=size):
            ok += 1
            continue
        try:
            imgs_s, mfu = bench.run_yolov3(batch_size=bs, size=size)
            record({"config": "yolov3", "bs": bs, "size": size,
                    "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "yolov3", "bs": bs, "size": size,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "yolo_stage_done"})


def run_ocr():
    """The OCR half of BASELINE config 4: CRNN recognition at PP-OCR's
    32xW crop shape.  Own stage marker — a wedge after the yolo sweep
    must not bank this stage as done."""
    import bench
    ok = 0
    for bs in (64, 128):
        if banked(config="crnn", bs=bs):
            ok += 1
            continue
        try:
            imgs_s, mfu = bench.run_crnn(batch_size=bs)
            record({"config": "crnn", "bs": bs,
                    "imgs_s": round(imgs_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "crnn", "bs": bs,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "ocr_stage_done"})


def run_moe():
    """First-ever on-chip GPT-MoE numbers (BASELINE config 5): bs sweep
    on the default top-k gate, plus one gshard trial."""
    import bench
    ok = 0
    for bs, gate in ((8, "topk"), (16, "topk"), (8, "gshard")):
        if banked(config="gpt_moe", bs=bs, gate=gate):
            ok += 1
            continue
        try:
            tok_s, mfu = bench.run_gpt_moe(batch_size=bs, gate=gate)
            record({"config": "gpt_moe", "bs": bs, "gate": gate,
                    "tok_s": round(tok_s, 1), "mfu": round(mfu, 4)})
            ok += 1
        except Exception as e:
            record({"config": "gpt_moe", "bs": bs, "gate": gate,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "moe_stage_done"})


def run_decode():
    """On-chip serving numbers: decode tok/s vs HBM roofline for bf16 /
    a8w8 / w4a16, plus the speculative wall-clock ceiling (both were
    CPU-only until the tunnel returned)."""
    import bench
    ok = 0
    for quant in (None, "a8w8", "w4a16"):
        if banked(config="decode", quant=quant or "bf16"):
            ok += 1
            continue
        try:
            r = bench.run_decode(quant=quant)
            record({"config": "decode", "quant": quant or "bf16", **r})
            ok += 1
        except Exception as e:
            record({"config": "decode", "quant": quant or "bf16",
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    try:
        record({"config": "speculative", **bench.run_speculative()})
        ok += 1
    except Exception as e:
        record({"config": "speculative",
                "error": f"{type(e).__name__}: {str(e)[:160]}"})
    if ok:
        record({"config": "decode_stage_done"})


# the full GPT candidate grid. bs7/dots probes the last step before the
# bs8/dots compile cliff; bs8/dots/accum2 gets effective batch 8 at
# microbatch-4 peak memory (gradient-merge scan), sidestepping that
# cliff entirely; bs6/accum2 amortizes the optimizer+grad-clip epilogue
# over an effective batch of 12 at bs6's proven-safe peak memory
GPT_GRID = (
    ("gpt_1p3b", 4, "dots", 1), ("gpt_1p3b", 6, "dots", 1),
    ("gpt_1p3b", 6, "dots", 2), ("gpt_1p3b", 7, "dots", 1),
    ("gpt_1p3b", 8, "dots", 2), ("gpt_1p3b", 8, "full", 1))


def _advisor_top(grid, top=2):
    """Static remat/microbatch advisor selection: rank the grid by
    replayed peak + roofline throughput (paddle_tpu.analysis.autotune —
    host-side tracing only, no compile, no device work) and keep the
    top candidates. The on-chip stage then measures only those."""
    from paddle_tpu.analysis.autotune import rank_gpt_candidates
    return rank_gpt_candidates(list(grid), top=top, log=log)


def best_gpt_config(path="perf_campaign_results.jsonl"):
    """Strongest successful gpt trial on record (by mfu), or None —
    what the stage reports as its answer regardless of how many grid
    points this run measured."""
    best = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "error" in row or "mfu" not in row or \
                        not str(row.get("config", "")).startswith("gpt_1p3b"):
                    continue
                if best is None or row["mfu"] > best["mfu"]:
                    best = row
    except OSError:
        pass
    return best


def run_gpt(exhaustive=False):
    """Measure the GPT grid: by default only the static advisor's top-2
    candidates (≥2x less tunnel exposure than the 6-point grid, and the
    advisor's #1 is the measured best on the cached campaign);
    --exhaustive restores the full sweep. Advisor failure or an
    all-banked selection falls back to the full grid, so no trial is
    ever unreachable."""
    import bench
    grid = list(GPT_GRID)
    chosen = grid
    if not exhaustive:
        try:
            chosen = _advisor_top(grid)
            log(f"advisor selected {len(chosen)}/{len(grid)} candidates: "
                f"{chosen}")
        except Exception as e:
            log(f"advisor failed ({type(e).__name__}: {str(e)[:160]}); "
                "measuring the full grid")
            chosen = grid
        else:
            if all(banked(config=n, bs=b, remat=r, accum=a,
                          _defaults={"accum": 1})
                   for n, b, r, a in chosen):
                # the advisor's picks are already measured: widen to the
                # full grid so repeat runs reach the remaining points
                # instead of leaving them permanently unmeasured
                log("advisor's picks already banked; widening to the "
                    "full grid")
                chosen = grid
    ok = 0
    for name, bs, rp, accum in chosen:
        if banked(config=name, bs=bs, remat=rp, accum=accum,
                  _defaults={"accum": 1}):
            ok += 1
            continue
        try:
            tok_s, mfu, _, static_hbm = bench.run_config(
                name, bs, 1024, remat_policy=rp, grad_accum=accum)
            record({"config": name, "bs": bs, "remat": rp, "accum": accum,
                    "tok_s": round(tok_s, 1), "mfu": round(mfu, 4),
                    "static_peak_hbm": static_hbm})
            ok += 1
        except Exception as e:
            record({"config": name, "bs": bs, "remat": rp, "accum": accum,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"})
            import gc
            gc.collect()
    if ok:
        record({"config": "gpt_stage_done"})
    best = best_gpt_config()
    if best:
        log(f"gpt stage best on record: bs{best['bs']}/"
            f"{best.get('remat', '?')}"
            + (f"/accum{best['accum']}" if best.get("accum", 1) > 1 else "")
            + f" mfu={best['mfu']}")


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    which = args[0] if args else "all"
    # --exhaustive: measure the FULL gpt grid instead of the static
    # advisor's top-2 (use when the advisor's model is in question)
    exhaustive = "--exhaustive" in sys.argv[1:]
    if which in ("resnet", "all"):
        run_resnet()
    if which in ("hlo",):
        run_hlo_audit()
    if which in ("bert", "all"):
        run_bert()
    if which in ("tune",):
        run_flash_tune()
    if which in ("yolo", "all"):
        run_yolo()
    if which in ("ocr", "crnn", "all"):
        run_ocr()
    if which in ("moe", "all"):
        run_moe()
    if which in ("gpt", "all"):
        run_gpt(exhaustive=exhaustive)
    if which in ("decode", "all"):
        run_decode()


if __name__ == "__main__":
    main()
