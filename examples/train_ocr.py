"""PP-OCR-style pipeline recipe: CRNN recognition (CTC) + DBNet detection.

Synthetic-data rendering of the PaddleOCR rec_crnn / det_db training
loops. The recognizer reads 32xW crops and emits one CTC distribution per
W/4 column; the detector emits a shrink-probability map. Both train as one
jitted step each.

    python examples/train_ocr.py --task rec --steps 50
    python examples/train_ocr.py --task det --steps 50
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.trainer import LossBuffer, Trainer
from paddle_tpu.io import prefetch_to_device
from paddle_tpu.vision.models import CRNN, DBNet

CHARSET = "0123456789abcdefghijklmnopqrstuvwxyz"   # + blank at id 0


def rec_batches(batch_size, width=96, max_len=8, seed=0):
    rng = np.random.RandomState(seed)
    n_cls = len(CHARSET) + 1
    while True:
        lens = rng.randint(2, max_len + 1, batch_size)
        labels = rng.randint(1, n_cls, (batch_size, max_len))
        labels *= (np.arange(max_len)[None, :] < lens[:, None])
        yield {"image": rng.randn(batch_size, 3, 32, width).astype("float32"),
               "label": labels.astype("int32"),
               "length": lens.astype("int32")}


def det_batches(batch_size, size=128, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        gt = np.zeros((batch_size, 1, size, size), np.float32)
        for i in range(batch_size):
            x0, y0 = rng.randint(0, size // 2, 2)
            w, h = rng.randint(size // 8, size // 2, 2)
            gt[i, 0, y0:y0 + h, x0:x0 + w] = 1.0
        yield {"image": rng.randn(batch_size, 3, size, size).astype("float32"),
               "gt": gt}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", choices=["rec", "det"], default="rec")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    paddle.seed(0)
    build_mesh(dp=1)
    if args.task == "rec":
        model = CRNN(num_classes=len(CHARSET) + 1)
        batches = rec_batches(args.batch_size)

        def loss_fn(m, b):
            logits = m(paddle.to_tensor(b["image"]))
            return m.loss(logits, paddle.to_tensor(b["label"]),
                          paddle.to_tensor(b["length"]))
    else:
        model = DBNet()
        batches = det_batches(args.batch_size)

        def loss_fn(m, b):
            prob = m(paddle.to_tensor(b["image"]))
            return m.loss(prob, paddle.to_tensor(b["gt"]))

    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    trainer = Trainer(model, opt, loss_fn)
    # prefetch_to_device: synthetic batches are assembled + sharded onto
    # the mesh in a background thread; LossBuffer batches the host syncs
    batches = prefetch_to_device(batches, depth=2)
    losses = LossBuffer(drain_every=10)
    for step in range(1, args.steps + 1):
        losses.append(trainer.step(next(batches)))
        if step % 10 == 0 or step == 1:
            print(f"step {step}: loss={losses.drain():.4f}")
    if args.task == "rec":
        trainer.sync_to_model()
        ids = model.decode_greedy(model(paddle.to_tensor(
            next(batches)["image"])))
        first = [CHARSET[i - 1] for i in np.asarray(ids.numpy())[0] if i > 0]
        print("sample decode:", "".join(first) or "<empty>")


if __name__ == "__main__":
    main()
