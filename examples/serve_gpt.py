"""Text-in/text-out GPT serving demo: WordPiece tokenizer (native C++
runtime) + continuous-batching paged-KV decode engine.

Run:  JAX_PLATFORMS=cpu PYTHONPATH=. python examples/serve_gpt.py [a8w8|w4a16]
(The model is randomly initialized — outputs are gibberish tokens; the
point is the full serving path: tokenize -> prefill -> batched sampled
decode -> detokenize. Swap in converted weights via
utils.apply_reference_checkpoint for real outputs.)

--replicas N serves the same prompts through a FleetRouter over N
engine replicas sharing one host KV tier (docs/serving.md "Fleet
serving") — the printed streams are byte-identical to the N=1 run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.models import GPT, gpt_tiny
from paddle_tpu.runtime.tokenizer import WordPieceTokenizer
from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder


def build_tokenizer():
    """Tiny demo vocab: real deployments load a bert-style vocab.txt."""
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy",
             "dog", "tpu", "chips", "compile", "fast", "##s", "##ing"]
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]"] + words + \
        [chr(c) for c in range(ord("a"), ord("z") + 1)]
    return WordPieceTokenizer(vocab), len(vocab)


def serve_fleet(model, tok, quant, n, trace_path, cache_dir,
                multi_tenant):
    """--replicas N: the same prompts through a FleetRouter over N
    engine replicas. Requests route by prefix affinity — the shared
    system page sends every prompt here to ONE replica, so its pages
    prefill once fleet-wide — with a global rid order that makes the
    token streams byte-identical to the single-engine run. The
    replicas share one host KV tier (file-backed under --cache-dir,
    else a temp dir), so a respawned replica warm-starts from its
    siblings' spilled pages."""
    import tempfile

    from paddle_tpu.serving import (FleetRouter, PagedGPTDecoder,
                                    PrefixCache, SharedHostKVTier,
                                    TenantEngine)
    tier_dir = cache_dir or tempfile.mkdtemp(prefix="serve_gpt_tier_")
    engines = []
    for _ in range(n):
        dec = PagedGPTDecoder(model, num_pages=64, page_size=16,
                              max_batch=4, temperature=0.8, top_p=0.95,
                              seed=0, quant=quant)
        tier = SharedHostKVTier(tier_dir, fingerprint=dec)
        cache = PrefixCache(dec.page_size, salt=dec.cache_fingerprint(),
                            tier=tier)
        engines.append(TenantEngine(dec, max_new_tokens=16,
                                    trace=bool(trace_path),
                                    prefix_cache=cache))
    router = FleetRouter(engines)
    dec = engines[0].d
    system = (tok.encode("the quick brown fox jumps over the lazy dog")
              * 4)[:dec.page_size]
    prompts = ["the quick brown fox", "tpu chips compile fast",
               "the lazy dog"]
    gids = {}
    for k, p in enumerate(prompts):
        ids = np.asarray(system + tok.encode(p), np.int32) % 256
        tenant, slo = (("chat", "latency") if multi_tenant and k == 0
                       else ("default", "throughput"))
        gids[router.submit(ids, tenant=tenant, slo=slo)] = p
    outs = router.run()
    for gid, p in gids.items():
        toks = [t % dec.cfg.vocab_size for t in outs[gid]]
        print(f"{p!r} -> replica {router.replica_of(gid)}: "
              f"{len(outs[gid])} tokens: {toks[:8]}...")
    s = router.merged_stats().summary()
    print(f"fleet of {n}: {s['requests']} prompts, {s['tokens']} "
          f"tokens, prefix hit rate {s.get('prefix_hit_rate', 0.0):.3f}"
          f" (the shared system page prefills ONCE fleet-wide), "
          f"shared tier {engines[0].cache.tier.n_entries} entr(ies) "
          f"under {tier_dir}")
    if multi_tenant:
        import json
        print("fleet tenancy summary:")
        print(json.dumps(router.tenancy_summary(), indent=1,
                         sort_keys=True))
    if trace_path:
        router.export_trace(trace_path)
        print(f"fleet flight trace -> {trace_path} (one merged "
              "timeline, a pid block per replica; load in Perfetto)")


def main():
    argv = sys.argv[1:]
    args, trace_path, cache_dir = [], None, None
    multi_tenant = False
    replicas = 1
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--multi-tenant":
            multi_tenant = True
        elif a.startswith("--replicas="):
            replicas = int(a.split("=", 1)[1])
        elif a == "--replicas":
            if i + 1 >= len(argv):
                sys.exit("usage: serve_gpt.py [--replicas N]")
            replicas = int(argv[i + 1])
            i += 1
        elif a.startswith("--trace="):
            trace_path = a.split("=", 1)[1]
        elif a == "--trace":
            if i + 1 >= len(argv):
                sys.exit("usage: serve_gpt.py [a8w8|w4a16] "
                         "[--trace PATH | --trace=PATH] "
                         "[--cache-dir DIR | --cache-dir=DIR]")
            trace_path = argv[i + 1]
            i += 1
        elif a.startswith("--cache-dir="):
            cache_dir = a.split("=", 1)[1]
        elif a == "--cache-dir":
            if i + 1 >= len(argv):
                sys.exit("usage: serve_gpt.py [a8w8|w4a16] "
                         "[--trace PATH | --trace=PATH] "
                         "[--cache-dir DIR | --cache-dir=DIR]")
            cache_dir = argv[i + 1]
            i += 1
        else:
            args.append(a)
        i += 1
    quant = args[0] if args else None
    paddle.seed(0)
    build_mesh(dp=1)
    tok, vocab_size = build_tokenizer()
    model = GPT(gpt_tiny(vocab_size=256, max_seq_len=128,
                         dtype="float32", remat=False))
    model.eval()
    if replicas > 1:
        serve_fleet(model, tok, quant, replicas, trace_path, cache_dir,
                    multi_tenant)
        return
    dec = PagedGPTDecoder(model, num_pages=64, page_size=16, max_batch=4,
                          temperature=0.8, top_p=0.95, seed=0, quant=quant)
    # k_max defaults to cost_model.decode_horizon's priced K: blocks of
    # K decode ticks run device-resident (one compiled lax.scan), the
    # host syncing only at block boundaries for admission/retirement.
    # Admission is RAGGED by default: prompts stream into those same
    # horizons as token-budgeted chunks (serving.RaggedScheduler), so
    # a long prompt never stalls the other slots behind a blocking
    # prefill dispatch (docs/serving.md "Ragged scheduling").
    # --trace=/path.json attaches the flight recorder: per-request
    # lifecycle spans + per-horizon tick records with roofline drift,
    # exported as one Perfetto-viewable chrome trace
    # (docs/observability.md)
    # --cache-dir=DIR: TIERED prefix cache that OUTLIVES the engine
    # (docs/serving.md "Tiered KV"): first run prefills the shared
    # system-prompt block, saves pool + chain index + host-tier
    # entries keyed by the decoder fingerprint; a second run of this
    # script warm-starts — the shared block mounts host-side with
    # ZERO prefill compute, and the TTFT line below shows it. A
    # fingerprint-mismatched decoder (different weights/quant) refuses
    # the saved cache with a clear error.
    cache = None
    warm = False
    if cache_dir:
        from paddle_tpu.serving import HostKVTier, PrefixCache
        if os.path.exists(os.path.join(cache_dir, "index.json")):
            cache = PrefixCache.load(cache_dir, dec, tier=HostKVTier())
            warm = True
            print(f"warm start: loaded {cache.n_pages} cached page(s) "
                  f"+ {cache.tier.n_entries if cache.tier else 0} "
                  f"host-tier entr(ies) from {cache_dir}")
        else:
            cache = PrefixCache(dec.page_size,
                                salt=dec.cache_fingerprint(),
                                tier=HostKVTier())
    # --multi-tenant: the SAME serving path through the TenantEngine —
    # two tenants sharing the slots/page pool, an interactive one under
    # the latency SLO (admits ahead, may preempt by page-spill) and a
    # bulk one under throughput (backfills); the per-tenant ledgers and
    # pooled SLO tails print below (docs/serving.md "Multi-tenant
    # serving")
    if multi_tenant:
        from paddle_tpu.serving import TenantEngine
        eng = TenantEngine(dec, max_new_tokens=16,
                           trace=bool(trace_path), prefix_cache=cache)
    else:
        eng = ContinuousBatchingEngine(dec, max_new_tokens=16,
                                       trace=bool(trace_path),
                                       prefix_cache=cache)

    # one shared SYSTEM prompt padded to a full 16-token page — the
    # cacheable block every request mounts (partial trailing blocks
    # are never cacheable)
    system = (tok.encode("the quick brown fox jumps over the lazy dog")
              * 4)[:dec.page_size]
    prompts = ["the quick brown fox", "tpu chips compile fast",
               "the lazy dog"]
    rids = {}
    for k, p in enumerate(prompts):
        ids = np.asarray(system + tok.encode(p), np.int32) % 256
        if multi_tenant:
            # first prompt plays the interactive chat tenant; the rest
            # are the batch tenant's backlog
            tenant, slo = (("chat", "latency") if k == 0
                           else ("batch", "throughput"))
            rids[eng.submit(ids, tenant=tenant, slo=slo)] = p
        else:
            rids[eng.submit(ids)] = p
    outs = eng.run()
    for rid, p in rids.items():
        toks = [t % dec.cfg.vocab_size for t in outs[rid]]
        print(f"{p!r} -> {len(outs[rid])} tokens in "
              f"{eng.steps} engine ticks: {toks[:8]}...")
    s = paddle.debug.serving_stats()[-1]
    print(f"served {s['requests']} prompts through "
          f"{dec.max_batch}-slot continuous batching: "
          f"{s['tokens']} tokens, K={s['k_max']} multi-step horizons, "
          f"{s['host_syncs_per_token']:.3f} host syncs/token "
          f"(per-tick engine pays ~1), "
          f"{s.get('prefill_chunks', 0)} ragged prompt chunks / "
          f"{s['prefill_syncs']} blocking prefill syncs, "
          f"p50 {s.get('token_p50_ms', 0)} ms/token")
    if multi_tenant:
        import json
        summary = eng.tenancy_summary()
        print("tenancy summary:")
        print(json.dumps(summary, indent=1, sort_keys=True))
    if cache is not None:
        print(f"prefix cache ({'warm' if warm else 'cold'}): "
              f"{s.get('prefix_hits', 0)} block hits, "
              f"{s.get('prefix_tokens_saved', 0)} prompt tokens never "
              f"prefilled, ttft p50 {s.get('ttft_p50_ms', 0)} ms"
              + (f", {s.get('tier_restores', 0)} host-tier restores"
                 if s.get('tier_restores') else ""))
        eng.cache.save(cache_dir)
        print(f"cache saved -> {cache_dir} (rerun for a warm start; "
              "a different model/quant config will refuse it)")
    if trace_path:
        from paddle_tpu.serving import export_chrome_trace
        export_chrome_trace(trace_path, recorders=eng.trace)
        drift = eng.trace.drift_report()
        print(f"flight trace -> {trace_path} "
              f"({len(eng.trace.events)} events, "
              f"{sum(d['drifting'] for d in drift)} drifting shapes; "
              "load in Perfetto / chrome://tracing)")


if __name__ == "__main__":
    main()
