"""BERT sequence-classification fine-tuning — the paddle_tpu rendering of
the reference's PaddleNLP BERT finetune recipe (bf16, masked flash
attention, AdamW + linear warmup, one compiled step).

Usage (synthetic token data):
    python examples/finetune_bert.py --steps 50
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse
import time

import numpy as np


def synthetic_batches(vocab, batch, seq, classes, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        lengths = rng.randint(seq // 2, seq + 1, (batch,))
        ids = rng.randint(4, vocab, (batch, seq)).astype("int32")
        mask = (np.arange(seq)[None, :] < lengths[:, None]).astype("int32")
        ids[mask == 0] = 0  # pad id
        yield {"input_ids": ids, "attention_mask": mask,
               "labels": rng.randint(0, classes, (batch,)).astype("int64")}


def text_batches(texts, labels, tok, batch, seq):
    """Real-text variant: the native C++ WordPiece tokenizer
    (paddle_tpu.runtime.WordPieceTokenizer, off-GIL batch encode with a
    bit-identical Python fallback) feeds the same model.

        tok ids come out [batch, seq] zero-padded with [CLS]/[SEP] added;
        attention_mask derives from the returned lengths.
    """
    n = len(texts)
    i = 0
    while True:
        sel = [(i + j) % n for j in range(batch)]
        i = (i + batch) % n
        ids, lens = tok.encode_batch([texts[s] for s in sel], max_len=seq)
        mask = (np.arange(seq)[None, :] < lens[:, None]).astype("int32")
        yield {"input_ids": ids, "attention_mask": mask,
               "labels": np.asarray([labels[s] for s in sel], np.int64)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="bert_base", choices=["bert_tiny", "bert_base", "bert_large"])
    ap.add_argument("--classes", type=int, default=2)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=2e-5)
    ap.add_argument("--from-ckpt", default=None, help=".pdparams to warm-start")
    ap.add_argument("--vocab-file", default=None,
                    help="WordPiece vocab (one token/line): tokenize real "
                         "text from --text-file instead of synthetic ids")
    ap.add_argument("--text-file", default=None,
                    help="TSV of '<label>\\t<text>' lines for --vocab-file")
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu.distributed import Trainer, build_mesh
    from paddle_tpu.models import bert

    cfg = getattr(bert, args.config)(dtype="bfloat16")

    # validate the data flags BEFORE spending time/memory on the model
    if bool(args.vocab_file) != bool(args.text_file):
        ap.error("--vocab-file and --text-file must be given together")
    tok = None
    if args.vocab_file:
        from paddle_tpu.runtime import WordPieceTokenizer
        tok = WordPieceTokenizer(args.vocab_file, lowercase=True)
        if tok.vocab_size > cfg.vocab_size:
            ap.error(f"vocab file has {tok.vocab_size} tokens > model "
                     f"embedding table {cfg.vocab_size}; ids would gather "
                     "garbage")
        labels, texts = [], []
        for ln, l in enumerate(open(args.text_file), 1):
            if not l.strip():
                continue
            parts = l.rstrip("\n").split("\t", 1)
            if len(parts) != 2 or not parts[0].strip().lstrip("-").isdigit():
                ap.error(f"{args.text_file}:{ln}: expected "
                         f"'<int label>\\t<text>'")
            labels.append(int(parts[0]))
            texts.append(parts[1])
        data = text_batches(texts, labels, tok, args.batch, args.seq)
    else:
        data = synthetic_batches(cfg.vocab_size, args.batch, args.seq,
                                 args.classes)

    paddle.seed(0)
    build_mesh()
    model = bert.BertForSequenceClassification(cfg, num_classes=args.classes)
    model.bfloat16()
    if args.from_ckpt:
        model.set_state_dict(paddle.load(args.from_ckpt))
    model.train()

    sched = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.PolynomialDecay(args.lr, args.steps), args.steps // 10,
        0.0, args.lr)
    opt = paddle.optimizer.AdamW(learning_rate=sched, weight_decay=0.01)

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]),
                   attention_mask=paddle.to_tensor(batch["attention_mask"]))
        return paddle.nn.functional.cross_entropy(
            logits, paddle.to_tensor(batch["labels"]))

    trainer = Trainer(model, opt, loss_fn)
    t0 = time.time()
    for step, batch in zip(range(1, args.steps + 1), data):
        loss = trainer.step(batch)
        if step % 20 == 0:
            dt = (time.time() - t0) / 20
            print(f"step {step}: loss {float(loss):.4f}  "
                  f"{args.batch / dt:.1f} seqs/s")
            t0 = time.time()
    trainer.sync_to_model()
    paddle.save(model.state_dict(), "bert_finetuned.pdparams")
    print("saved bert_finetuned.pdparams")


if __name__ == "__main__":
    main()
