"""ResNet training recipe — the paddle_tpu rendering of the reference's
PaddleClas ResNet run: channel-last layout, bf16 on the MXU, multiprocess
DataLoader with the native (off-GIL) JPEG pipeline, one compiled step.

Usage (synthetic data):
    python examples/train_resnet.py --steps 50
With an image-folder dataset (class-per-subdir of JPEGs):
    python examples/train_resnet.py --data /path/to/train --classes 1000
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse
import time

import numpy as np


def make_folder_dataset(root, size=224, channels_last=True):
    """vision.datasets.DatasetFolder with the native (off-GIL) JPEG pipeline
    as its loader: decode -> resize -> normalize in one C call per image."""
    from paddle_tpu.runtime.image import decode_resize_normalize
    from paddle_tpu.vision.datasets import DatasetFolder

    mean, std = [0.485, 0.456, 0.406], [0.229, 0.224, 0.225]

    def load(path):
        with open(path, "rb") as f:
            chw = decode_resize_normalize(f.read(), (size, size), mean, std)
        if chw.shape[0] == 1:          # grayscale JPEGs -> 3 channels
            chw = np.repeat(chw, 3, axis=0)
        x = np.transpose(chw, (1, 2, 0)) if channels_last else chw
        return x.astype(np.float32)

    return DatasetFolder(root, loader=load, extensions=(".jpg", ".jpeg"))


class SyntheticDataset:
    def __init__(self, n=4096, size=224, classes=1000, channels_last=True):
        self.n, self.size, self.classes = n, size, classes
        self.channels_last = channels_last

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        shape = (self.size, self.size, 3) if self.channels_last else (3, self.size, self.size)
        return rng.randn(*shape).astype(np.float32), np.int64(i % self.classes)

    def __len__(self):
        return self.n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="image-folder root (JPEGs)")
    ap.add_argument("--arch", default="resnet50")
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--workers", type=int, default=4)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu.distributed import LossBuffer, Trainer, build_mesh
    from paddle_tpu.io import DataLoader, DeviceLoader

    paddle.seed(0)
    build_mesh()  # dp over all attached devices

    # NHWC end-to-end: channels ride the TPU lane dim (docs/performance.md)
    model = getattr(paddle.vision.models, args.arch)(
        num_classes=args.classes, data_format="NHWC")
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Momentum(
        learning_rate=paddle.optimizer.lr.CosineAnnealingDecay(args.lr, args.steps),
        momentum=0.9, weight_decay=1e-4)

    def loss_fn(m, batch):
        return paddle.nn.functional.cross_entropy(
            m(paddle.to_tensor(batch["image"])), paddle.to_tensor(batch["label"]))

    trainer = Trainer(model, opt, loss_fn)
    ds = make_folder_dataset(args.data) if args.data else SyntheticDataset(classes=args.classes)
    if len(ds) < args.batch:
        raise SystemExit(f"dataset has {len(ds)} samples < --batch {args.batch}; "
                         "lower --batch (drop_last would yield zero batches)")
    loader = DataLoader(ds, batch_size=args.batch, shuffle=True, drop_last=True,
                        num_workers=args.workers, persistent_workers=True)
    # device-side prefetch: worker batches are sharded + H2D-copied two
    # steps ahead; the step loop never blocks on input OR on the loss
    dloader = DeviceLoader(loader, depth=2)
    losses = LossBuffer(drain_every=10)

    step, t0 = 0, time.time()
    while step < args.steps:
        for image, label in dloader:
            losses.append(trainer.step({"image": image, "label": label}))
            step += 1
            if step % 10 == 0:
                dt = (time.time() - t0) / 10
                print(f"step {step}: loss {losses.drain():.4f}  "
                      f"{args.batch / dt:.0f} imgs/s")
                t0 = time.time()
            if step >= args.steps:
                break
    losses.drain()
    dloader.close()
    print(f"input pipeline: {dloader.stats.snapshot()}")
    trainer.sync_to_model()  # params + BN running stats back into the Layer
    paddle.save(model.state_dict(), f"{args.arch}.pdparams")
    print(f"saved {args.arch}.pdparams")


if __name__ == "__main__":
    main()
