"""YOLOv3 detection training recipe (BASELINE config: PP-YOLOE/detection).

Synthetic-data variant of the PaddleDetection yolov3_darknet53_270e_coco
recipe: NHWC layout for the MXU, bf16 compute, one jitted step (fwd +
3-scale yolo_loss + momentum update). Swap `synthetic_batches` for a
DataLoader over your dataset; boxes are [cx, cy, w, h] normalized, labels
int32, both padded to `max_boxes` per image (pad with w=h=0).

Measured single v5e chip, 320x320, bs16: ~504 imgs/s.

    python examples/train_yolov3.py --steps 100 --batch-size 16
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.trainer import Trainer
from paddle_tpu.vision.models import yolov3_darknet53


def synthetic_batches(batch_size, size, max_boxes=8, seed=0):
    rng = np.random.RandomState(seed)
    while True:
        n_real = rng.randint(1, max_boxes + 1, batch_size)
        wh = rng.uniform(0.05, 0.5, (batch_size, max_boxes, 2))
        cxy = rng.uniform(0.2, 0.8, (batch_size, max_boxes, 2))
        mask = np.arange(max_boxes)[None, :] < n_real[:, None]
        boxes = np.concatenate([cxy, wh * mask[..., None]], -1)
        yield {
            "image": rng.randn(batch_size, size, size, 3).astype("float32"),
            "gt_box": boxes.astype("float32"),
            "gt_label": rng.randint(0, 80, (batch_size, max_boxes)).astype("int32"),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--size", type=int, default=320)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    paddle.seed(0)
    build_mesh(dp=1)
    model = yolov3_darknet53(num_classes=80, data_format="NHWC")
    model.bfloat16()
    model.train()
    sched = paddle.optimizer.lr.CosineAnnealingDecay(args.lr, args.steps)
    opt = paddle.optimizer.Momentum(learning_rate=sched, momentum=0.9,
                                    weight_decay=5e-4)

    def loss_fn(m, b):
        outs = m(paddle.to_tensor(b["image"]))
        return m.loss(outs, paddle.to_tensor(b["gt_box"]),
                      paddle.to_tensor(b["gt_label"]))

    trainer = Trainer(model, opt, loss_fn)
    it = synthetic_batches(args.batch_size, args.size)
    t0 = time.time()
    for step in range(1, args.steps + 1):
        loss = trainer.step(next(it))
        if step % 10 == 0 or step == 1:
            dt = (time.time() - t0) / step
            print(f"step {step}: loss={float(loss):.3f} "
                  f"{args.batch_size / dt:.0f} imgs/s")


if __name__ == "__main__":
    main()
