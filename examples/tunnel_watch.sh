#!/bin/bash
# Watch for the axon TPU tunnel to come back; the moment a device answers,
# fire the perf campaign and bench.py so a returning chip converts to
# recorded numbers within minutes, not hours.
#
# Lessons from the round-4 flaps (tunnel answered jax.devices() at 01:01,
# wedged on the first bulk transfer by 01:44; answered again at 03:16,
# wedged mid-measure at 03:21):
#   - the probe must exercise transfer + compile, not just device init,
#     or a half-up tunnel fires the 1.3B campaign into a hang — the probe
#     IS examples/tunnel_probe.py --quick (one implementation, no drift);
#   - loop forever and skip stages that already recorded results, so a
#     short tunnel window banks the small configs before the big ones;
#   - a stage is banked only when its "<stage>_stage_done" marker exists:
#     per-trial errors inside a completed sweep don't force a redo, but a
#     stage killed mid-run (timeout/wedge) has no marker and is retried;
#   - smallest-first order (resnet 25M, bert 110M, gpt 1.3B);
#   - bench.py itself carries mid-run SIGALRM + hard-exit watchdogs, so
#     the final full run cannot hang the loop either.
cd "$(dirname "$0")/.."

have() { grep -q "\"config\": \"$1_stage_done\"" perf_campaign_results.jsonl 2>/dev/null; }

# never collide with the driver's end-of-round bench: stop watching
# after MAX_WATCH_S (default 8h) or when a STOP_WATCH file appears
START_TS=$(date +%s)
MAX_WATCH_S=${MAX_WATCH_S:-28800}

# deadline is re-checked before EVERY stage launch, not just per loop
# iteration — a probe success minutes before the deadline must not run
# a multi-hour campaign into the driver's end-of-round bench
alive() { [ ! -e STOP_WATCH ] && [ $(( $(date +%s) - START_TS )) -le "$MAX_WATCH_S" ]; }

while true; do
  if ! alive; then
    echo "$(date -u +%FT%TZ) watch deadline/stop reached — exiting" >> tunnel_watch.log
    break
  fi
  if timeout 180 python examples/tunnel_probe.py --quick 2>/dev/null | grep -q "PROBE OK"; then
    echo "$(date -u +%FT%TZ) tunnel UP — launching perf campaign" >> tunnel_watch.log
    alive && { have resnet || timeout 2400 python examples/perf_campaign.py resnet >> tunnel_watch.log 2>&1; }
    alive && { have bert   || timeout 2400 python examples/perf_campaign.py bert   >> tunnel_watch.log 2>&1; }
    alive && { have yolo   || timeout 2400 python examples/perf_campaign.py yolo   >> tunnel_watch.log 2>&1; }
    alive && { have ocr    || timeout 1800 python examples/perf_campaign.py ocr    >> tunnel_watch.log 2>&1; }
    alive && { have moe    || timeout 2400 python examples/perf_campaign.py moe    >> tunnel_watch.log 2>&1; }
    alive && { grep -q '"config": "resnet50_hlo_audit"' perf_campaign_results.jsonl 2>/dev/null \
                || timeout 1800 python examples/perf_campaign.py hlo >> tunnel_watch.log 2>&1; }
    alive && { have gpt    || timeout 3000 python examples/perf_campaign.py gpt    >> tunnel_watch.log 2>&1; }
    alive && { have decode || timeout 2400 python examples/perf_campaign.py decode >> tunnel_watch.log 2>&1; }
    if have resnet && have bert && have yolo && have ocr && have moe && have gpt && have decode; then
      alive && timeout 3600 python bench.py >> tunnel_watch.log 2>&1
      echo "$(date -u +%FT%TZ) campaign complete" >> tunnel_watch.log
      break
    fi
    echo "$(date -u +%FT%TZ) campaign incomplete — will retry" >> tunnel_watch.log
  else
    echo "$(date -u +%FT%TZ) tunnel still down" >> tunnel_watch.log
  fi
  sleep 300
done
