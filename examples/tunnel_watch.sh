#!/bin/bash
# Watch for the axon TPU tunnel to come back; the moment a device answers,
# fire the perf campaign (resnet + bert + gpt + hlo) and bench.py so a
# returning chip converts to recorded numbers within minutes, not hours.
# Probe is a subprocess with a hard timeout (a down tunnel HANGS device
# init forever rather than erroring).
cd "$(dirname "$0")/.."
PROBE='import jax; assert jax.devices()[0].platform != "cpu"; print("TPU-OK")'
while true; do
  if timeout 120 python -c "$PROBE" 2>/dev/null | grep -q TPU-OK; then
    echo "$(date -u +%FT%TZ) tunnel UP — launching perf campaign" >> tunnel_watch.log
    for cfg in hlo resnet bert gpt; do
      timeout 3000 python examples/perf_campaign.py "$cfg" \
        >> tunnel_watch.log 2>&1
    done
    timeout 3000 python bench.py >> tunnel_watch.log 2>&1
    echo "$(date -u +%FT%TZ) campaign complete" >> tunnel_watch.log
    break
  fi
  echo "$(date -u +%FT%TZ) tunnel still down" >> tunnel_watch.log
  sleep 900
done
