#!/bin/bash
# Watch for the axon TPU tunnel to come back; the moment a device answers,
# fire the perf campaign and bench.py so a returning chip converts to
# recorded numbers within minutes, not hours.
#
# Lessons from the round-4 flap (tunnel answered jax.devices() at 01:01,
# wedged on the first bulk transfer by 01:44):
#   - the probe must exercise transfer + compile, not just device init,
#     or a half-up tunnel fires the 1.3B campaign into a hang;
#   - loop forever and skip stages that already recorded results, so a
#     short tunnel window banks the small configs before the big ones;
#   - smallest-first order (resnet 25M, bert 110M, gpt 1.3B).
cd "$(dirname "$0")/.."
PROBE='
import time, jax, jax.numpy as jnp, numpy as np
t0=time.time(); d=jax.devices(); assert d[0].platform != "cpu", d
x=(jnp.ones(())+1); x.block_until_ready()
a=jax.device_put(np.ones((16,1024,256),np.float32)); a.block_until_ready()
f=jax.jit(lambda a: a@a); b=f(jnp.ones((1024,1024),jnp.bfloat16))
b.block_until_ready()
print(f"TPU-OK {time.time()-t0:.1f}s")'

have() { grep -q "\"config\": \"$1\"" perf_campaign_results.jsonl 2>/dev/null \
         && ! grep "\"config\": \"$1\"" perf_campaign_results.jsonl | tail -1 | grep -q '"error"'; }

while true; do
  if timeout 180 python -c "$PROBE" 2>/dev/null | grep -q TPU-OK; then
    echo "$(date -u +%FT%TZ) tunnel UP — launching perf campaign" >> tunnel_watch.log
    have resnet50   || timeout 2400 python examples/perf_campaign.py resnet >> tunnel_watch.log 2>&1
    have bert_base  || timeout 2400 python examples/perf_campaign.py bert   >> tunnel_watch.log 2>&1
    have resnet50_hlo_audit || timeout 1800 python examples/perf_campaign.py hlo >> tunnel_watch.log 2>&1
    have gpt_1p3b   || timeout 3000 python examples/perf_campaign.py gpt    >> tunnel_watch.log 2>&1
    have decode     || timeout 2400 python examples/perf_campaign.py decode >> tunnel_watch.log 2>&1
    if have resnet50 && have bert_base && have gpt_1p3b; then
      timeout 3000 python bench.py >> tunnel_watch.log 2>&1
      echo "$(date -u +%FT%TZ) campaign complete" >> tunnel_watch.log
      break
    fi
    echo "$(date -u +%FT%TZ) campaign incomplete — will retry" >> tunnel_watch.log
  else
    echo "$(date -u +%FT%TZ) tunnel still down" >> tunnel_watch.log
  fi
  sleep 300
done
