"""Two-process CPU gloo A/B for the chunked collective-matmul overlap.

Launch under the PR-4 harness (one host, two ranks, gloo):

    python -m paddle_tpu.distributed.launch --nnodes 1 \
        --nproc_per_node 2 --cpu_devices_per_rank 1 \
        examples/bench_overlap_ab.py out.json

Rank 0 measures, on the REAL two-process mesh, the four wall clocks the
overlap story is made of:

  compute_ms  the row-parallel matmul alone (no collective)
  wire_ms     the bulk psum alone (same payload, gloo loopback)
  bulk_ms     matmul + bulk psum (impl="bulk" — the serialized twin)
  ring_ms     matmul + chunked ring (impl="ring", n_chunks tiles)

plus the per-permute dispatch floor (a tiny ppermute round), and banks
a JSON metric line with the two predictions bracketing them:
serial_pred = compute + wire (nothing hides, dispatch free) and
overlap_pred = the cost model's chunked-overlap leg at the same
n_chunks with the MEASURED per-chunk launch overhead.  The committed
line in docs/performance.md pins `closer_to == "overlap"`: the
measured chunked step sits strictly closer to the overlap-aware
prediction than to the serial sum — the chunked leg prices what the
decomposed schedule actually costs.  (On this harness the box has one
core and gloo dispatch costs milliseconds, so the ring pays its chunk
overhead without concurrent silicon to buy it back — the bulk twin
stays the faster CPU path, and the JSON records that honestly too.
The hiding itself is the TPU story the schedule manifest pins.)
"""
import json
import sys
import time

import numpy as np

import paddle_tpu.distributed as dist

dist.init_parallel_env()

import jax                                             # noqa: E402
import jax.numpy as jnp                                # noqa: E402
from jax.sharding import PartitionSpec as P            # noqa: E402

from paddle_tpu.cost_model import chunked_overlap_time  # noqa: E402
from paddle_tpu.distributed.mesh import (build_mesh,    # noqa: E402
                                         compat_shard_map)
from paddle_tpu.ops.overlap import (                    # noqa: E402
    chunked_matmul_all_reduce)

M, K_LOCAL, N = 128, 512, 4096      # per-device dot [M,K] @ [K,N]
N_CHUNKS = 4
WARMUP, ITERS = 3, 15


def _median_ms(fn, *args):
    for _ in range(WARMUP):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    p = jax.device_count()
    mesh = build_mesh(tp=p)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(M, p * K_LOCAL) * 0.1, jnp.float32)
    w = jnp.asarray(rng.randn(p * K_LOCAL, N) * 0.1, jnp.float32)
    y = jnp.asarray(rng.randn(M, N) * 0.1, jnp.float32)

    def sm(body, n_in):
        return jax.jit(compat_shard_map(
            body, mesh,
            in_specs=(P(None, "tp"), P("tp", None))[:n_in] or (P(),),
            out_specs=P(), axis_names={"tp"}, check=False))

    compute = sm(lambda xs, ws: xs @ ws, 2)
    wire = jax.jit(compat_shard_map(
        lambda ys: jax.lax.psum(ys, "tp"), mesh, in_specs=(P(),),
        out_specs=P(), axis_names={"tp"}, check=False))
    bulk = sm(lambda xs, ws: chunked_matmul_all_reduce(
        xs, ws, "tp", impl="bulk"), 2)
    ring = sm(lambda xs, ws: chunked_matmul_all_reduce(
        xs, ws, "tp", n_chunks=N_CHUNKS, impl="ring"), 2)
    # per-permute dispatch floor: one tiny single-hop round on the
    # same gloo wire — the measured value of the cost model's
    # CHUNK_LAUNCH_OVERHEAD_S knob on this transport
    tiny = jax.jit(compat_shard_map(
        lambda v: jax.lax.ppermute(
            v, "tp", [(i, (i + 1) % p) for i in range(p)]),
        mesh, in_specs=(P(),), out_specs=P(None), axis_names={"tp"},
        check=False))

    # twin discipline holds over the real gloo wire too
    assert np.asarray(ring(x, w)).tobytes() == \
        np.asarray(bulk(x, w)).tobytes(), "ring != bulk over gloo"

    compute_ms = _median_ms(compute, x, w)
    wire_ms = _median_ms(wire, y)
    bulk_ms = _median_ms(bulk, x, w)
    ring_ms = _median_ms(ring, x, w)
    permute_ms = _median_ms(tiny, jnp.zeros((8,), jnp.float32))

    if jax.process_index() != 0:
        return
    serial_pred = compute_ms + wire_ms
    # divisible-path ring at p participants: p-1 reduce-scatter hops +
    # p-1 all-gather hops per chunk
    chunk_overhead_ms = 2 * (p - 1) * permute_ms
    ct = chunked_overlap_time(compute_ms / 1e3, wire_ms / 1e3,
                              n_chunks=N_CHUNKS,
                              launch_overhead_s=chunk_overhead_ms / 1e3)
    overlap_pred = ct.step_s * 1e3
    closer = ("overlap"
              if abs(ring_ms - overlap_pred) < abs(ring_ms - serial_pred)
              else "serial")
    metric = {
        "bench": "overlap_ab_two_process_gloo",
        "mesh": {"processes": jax.process_count(), "tp": p},
        "shape": {"m": M, "k_local": K_LOCAL, "n": N,
                  "dtype": "float32", "n_chunks": N_CHUNKS},
        "compute_ms": round(compute_ms, 3),
        "wire_ms": round(wire_ms, 3),
        "permute_dispatch_ms": round(permute_ms, 3),
        "bulk_ms": round(bulk_ms, 3),
        "ring_ms": round(ring_ms, 3),
        "serial_pred_ms": round(serial_pred, 3),
        "overlap_pred_ms": round(overlap_pred, 3),
        "closer_to": closer,
    }
    line = json.dumps(metric, sort_keys=True)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
