"""Input-pipeline microbenchmark: real on-disk JPEG folder through
DatasetFolder + DataLoader, comparing native libjpeg decode
(runtime/cxx/image_ops.cpp) vs PIL, and in-process vs process workers
(shared-memory transport). Plus a synthetic INPUT-BOUND training
workload comparing the synchronous feed (host batch + per-step
float(loss)) against io.DeviceLoader + LossBuffer (async sharded
prefetch, batched loss syncs) — printed as a bench.py-style
{"metric": ...} JSON line.

Run:  JAX_PLATFORMS=cpu PYTHONPATH=. python examples/bench_dataloader.py
      (only the device-feed half: ... bench_dataloader.py --device-feed-only)

Representative result (this machine — ONE cpu core, so worker overlap
cannot exceed 1x; on a multi-core host the worker rows scale with cores):

    decode only : native 1713 imgs/s vs PIL 1126 imgs/s  -> 1.52x
    pipeline w0 : native  601 imgs/s vs PIL  361 imgs/s  -> 1.66x
    pipeline w2 : native  367 imgs/s (1-core worker overhead; see
                  docs/performance.md)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import tempfile
import time

import numpy as np

import paddle_tpu  # noqa: F401  (registers runtime paths)
from paddle_tpu.io import DataLoader
from paddle_tpu.runtime import image as rimage
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import DatasetFolder, _load_image


def make_folder(root, n_per_class=64, size=224):
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i:03d}.jpg"),
                                      quality=90)


def pil_loader(path):
    from PIL import Image
    return np.asarray(Image.open(path).convert("RGB"))


def bench_decode(ds, label, n=128):
    t0 = time.perf_counter()
    for i in range(n):
        ds.loader(ds.samples[i % len(ds)][0])
    dt = time.perf_counter() - t0
    print(f"decode only [{label}]: {n / dt:7.0f} imgs/s")
    return n / dt


def bench_loader(ds, label, workers, epochs=2):
    loader = DataLoader(ds, batch_size=32, shuffle=False,
                        num_workers=workers)
    for _ in loader:        # warm (worker spin-up, first batches)
        break
    n = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for imgs, labels in loader:
            n += imgs.shape[0]
    dt = time.perf_counter() - t0
    print(f"pipeline [{label}, workers={workers}]: {n / dt:7.0f} imgs/s")
    return n / dt


def bench_device_feed(steps=60, batch=64, dim=512, hidden=2048, classes=10,
                      io_wait_ms=7.0):
    """Synchronous feed vs DeviceLoader on an INPUT-BOUND synthetic
    workload. Each batch costs `io_wait_ms` of off-GIL input wait (the
    stand-in for disk reads, native libjpeg decode, shm transport from
    worker processes — everything a real pipeline waits on outside the
    interpreter) plus numpy assembly. The synchronous loop serializes
    that wait with the compiled step; DeviceLoader hides it behind step
    N's compute. Prints ONE JSON line like bench.py.

    (On this CPU mesh the "device" step also burns host cores, so
    CPU-bound host transforms can't overlap — that half of the story
    only shows on a real chip; the I/O half shows anywhere.)"""
    import json

    import paddle_tpu as paddle
    from paddle_tpu.distributed import LossBuffer, build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.io import DeviceLoader

    build_mesh()
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(dim, hidden), paddle.nn.ReLU(),
        paddle.nn.Linear(hidden, classes))
    model.train()
    opt = paddle.optimizer.SGD(learning_rate=0.01)

    def loss_fn(m, b):
        return paddle.nn.functional.cross_entropy(
            m(paddle.to_tensor(b["x"])), paddle.to_tensor(b["y"]))

    trainer = Trainer(model, opt, loss_fn)
    labels = (np.arange(batch) % classes).astype(np.int32)

    def gen(n, seed=0):
        rng = np.random.RandomState(seed)
        for _ in range(n):
            time.sleep(io_wait_ms / 1e3)   # off-GIL input wait
            x = rng.randn(batch, dim).astype(np.float32)
            yield {"x": x, "y": labels}

    float(trainer.step(next(gen(1))))    # compile outside both timed loops

    t0 = time.perf_counter()             # sync: host feed + per-step fetch
    for b in gen(steps):
        float(trainer.step(b))
    sync_sps = steps / (time.perf_counter() - t0)

    loader = DeviceLoader(gen(steps), depth=2)
    losses = LossBuffer(drain_every=steps)
    t0 = time.perf_counter()             # async: prefetch + batched syncs
    for b in loader:
        losses.append(trainer.step(b))
    losses.drain()
    async_sps = steps / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "input_bound_steps_per_sec",
        "value": round(async_sps, 2), "unit": "steps/s",
        "sync_steps_per_sec": round(sync_sps, 2),
        "speedup": round(async_sps / sync_sps, 2),
        "pipeline": loader.stats.snapshot()}), flush=True)
    return async_sps, sync_sps


def main():
    if "--device-feed-only" in sys.argv:
        bench_device_feed()
        return
    root = tempfile.mkdtemp(prefix="bench_imgs_")
    make_folder(root)
    print(f"native decoder available: {rimage.native_available()}")
    tf = T.Compose([T.Resize(160), T.CenterCrop(128),
                    T.Normalize(mean=[127.5] * 3, std=[127.5] * 3,
                                data_format="HWC")])
    native_ds = DatasetFolder(root, transform=tf)          # native default
    pil_ds = DatasetFolder(root, loader=pil_loader, transform=tf)

    r = {}
    r["dec_native"] = bench_decode(DatasetFolder(root), "native")
    r["dec_pil"] = bench_decode(DatasetFolder(root, loader=pil_loader), "PIL")
    print(f"native decode speedup: {r['dec_native'] / r['dec_pil']:.2f}x")
    for label, ds in (("native", native_ds), ("PIL", pil_ds)):
        for w in (0, 2):
            r[f"{label}_w{w}"] = bench_loader(ds, label, w)
    print(f"end-to-end native vs PIL (w0): "
          f"{r['native_w0'] / r['PIL_w0']:.2f}x")
    bench_device_feed()


if __name__ == "__main__":
    main()
