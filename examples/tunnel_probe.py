"""Staged axon-tunnel health probe: answers WHERE the chip path stalls
(device init, host->device bandwidth, compile, execute) with one timed
line per stage, so a hung 1.3B campaign can be diagnosed in minutes.

    python examples/tunnel_probe.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def stage(name):
    print(f"[{time.strftime('%H:%M:%S')}] {name}...", flush=True)


def done(name, t0, extra=""):
    print(f"[{time.strftime('%H:%M:%S')}] {name}: {time.time()-t0:.1f}s"
          f" {extra}", flush=True)


def main():
    # --quick: the tunnel-watch health check (init + one bulk transfer +
    # compiled matmul — the three stages a half-up tunnel fails), one
    # "PROBE OK" line. Keeps the watch and the diagnostic probe on ONE
    # implementation instead of a drifting inline copy.
    quick = "--quick" in sys.argv

    stage("import jax + device init")
    t0 = time.time()
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    assert devs[0].platform != "cpu", devs
    done("device init", t0, f"devices={devs}")

    stage("tiny op (1-elem add)")
    t0 = time.time()
    x = jnp.ones(()) + 1
    x.block_until_ready()
    done("tiny op", t0)

    for mb in ((16,) if quick else (8, 64, 256)):
        stage(f"host->device transfer {mb}MB")
        t0 = time.time()
        arr = np.ones((mb, 1024, 1024 // 4), dtype=np.float32)
        d = jax.device_put(arr)
        d.block_until_ready()
        dt = time.time() - t0
        done(f"transfer {mb}MB", t0, f"= {mb / dt:.0f} MB/s")
        del d, arr

    stage("compile+run 4k x 4k bf16 matmul")
    t0 = time.time()
    a = jnp.ones((4096, 4096), jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    f(a).block_until_ready()
    done("matmul compile+run", t0)
    if quick:
        print("PROBE OK", flush=True)
        return
    t0 = time.time()
    for _ in range(10):
        a = f(a)
    a.block_until_ready()
    dt = (time.time() - t0) / 10
    done("matmul steady", t0, f"= {2 * 4096**3 / dt / 1e12:.1f} TFLOP/s")

    stage("on-device init of 1B bf16 params (no host transfer)")
    t0 = time.time()
    g = jax.jit(lambda k: [jax.random.normal(k, (4096, 4096), jnp.bfloat16)
                           for _ in range(60)])
    w = g(jax.random.PRNGKey(0))
    jax.block_until_ready(w)
    done("1B on-device init", t0)
    print("PROBE OK", flush=True)


if __name__ == "__main__":
    main()
