"""Tensor __getitem__/__setitem__ pinned against numpy semantics
(paddle follows numpy's advanced-indexing rules)."""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.RandomState(0)
X = RNG.randn(4, 5, 6).astype("float32")


def _wrap(v):
    return paddle.to_tensor(v)


GET_CASES = [
    ("int", lambda v, w: v[2]),
    ("neg_slice_step", lambda v, w: v[::-1, 1:4]),
    ("ellipsis", lambda v, w: v[..., 2]),
    ("newaxis", lambda v, w: v[:, None, 3]),
    ("int_array", lambda v, w: v[w(np.array([2, 0, 3]))]),
    ("bool_mask_full", lambda v, w: v[w(X > 0)]),
    ("bool_mask_axis0",
     lambda v, w: v[w(np.array([True, False, True, False]))]),
    ("two_int_arrays",
     lambda v, w: v[w(np.array([1, 2])), w(np.array([3, 4]))]),
    ("mixed_slice_array", lambda v, w: v[:, w(np.array([0, 2])), 1]),
]


@pytest.mark.parametrize("name,fn", GET_CASES,
                         ids=[c[0] for c in GET_CASES])
def test_getitem_matches_numpy(name, fn):
    ours = np.asarray(fn(paddle.to_tensor(X), _wrap)._value)
    want = fn(X, lambda v: v)
    assert ours.shape == want.shape
    np.testing.assert_allclose(ours, want, rtol=1e-6)


SET_CASES = [
    ("int", lambda v, w: v.__setitem__(1, 7.0)),
    ("strided", lambda v, w: v.__setitem__((slice(None, None, 2), 1), 3.0)),
    ("bool_mask", lambda v, w: v.__setitem__(w(X > 1), 0.0)),
    ("int_array_rows", lambda v, w: v.__setitem__(
        w(np.array([0, 2])), w(np.ones((2, 5, 6), "float32")))),
    ("multislice", lambda v, w: v.__setitem__(
        (slice(1, 3), slice(2, 4), 0), 9.0)),
]


@pytest.mark.parametrize("name,fn", SET_CASES,
                         ids=[c[0] for c in SET_CASES])
def test_setitem_matches_numpy(name, fn):
    ours = paddle.to_tensor(X.copy())
    fn(ours, _wrap)
    want = X.copy()
    fn(want, lambda v: np.asarray(v))
    np.testing.assert_allclose(np.asarray(ours._value), want, rtol=1e-6)
