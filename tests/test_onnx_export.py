"""ONNX export: jaxpr -> hand-encoded ModelProto (paddle_tpu/onnx.py).

Round-trips the emitted file with the module's own wire-format reader and
re-executes the decoded graph with a small numpy interpreter to check the
graph is semantically correct, not just well-formed.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import _decode_model, export
from paddle_tpu.static import InputSpec

_DT = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_, 11: np.float64}


def _run_graph(graph, feeds):
    """Tiny numpy executor for the node set our exporter emits."""
    env = dict(feeds)
    for name, t in graph["initializers"].items():
        env[name] = np.frombuffer(t["raw"], _DT[t["dtype"]]).reshape(t["dims"])

    def binop(fn):
        return lambda ins, at: fn(env[ins[0]], env[ins[1]])

    ops = {
        "Add": binop(np.add), "Sub": binop(np.subtract),
        "Mul": binop(np.multiply), "Div": binop(np.divide),
        "Pow": binop(np.power), "Max": binop(np.maximum),
        "Min": binop(np.minimum), "MatMul": binop(np.matmul),
        "Equal": binop(np.equal), "Greater": binop(np.greater),
        "Less": binop(np.less),
        "Tanh": lambda ins, at: np.tanh(env[ins[0]]),
        "Exp": lambda ins, at: np.exp(env[ins[0]]),
        "Log": lambda ins, at: np.log(env[ins[0]]),
        "Sqrt": lambda ins, at: np.sqrt(env[ins[0]]),
        "Neg": lambda ins, at: -env[ins[0]],
        "Abs": lambda ins, at: np.abs(env[ins[0]]),
        "Sigmoid": lambda ins, at: 1 / (1 + np.exp(-env[ins[0]])),
        "Reciprocal": lambda ins, at: 1.0 / env[ins[0]],
        "Erf": lambda ins, at: _erf(env[ins[0]]),
        "Reshape": lambda ins, at: env[ins[0]].reshape(env[ins[1]].astype(int)),
        "Expand": lambda ins, at: np.broadcast_to(
            env[ins[0]], tuple(env[ins[1]].astype(int))),
        "Transpose": lambda ins, at: np.transpose(env[ins[0]], at["perm"]),
        "Cast": lambda ins, at: env[ins[0]].astype(_DT[at["to"]]),
        "Where": lambda ins, at: np.where(env[ins[0]], env[ins[1]], env[ins[2]]),
        "Concat": lambda ins, at: np.concatenate([env[i] for i in ins],
                                                 axis=_signed(at["axis"])),
        "Gather": lambda ins, at: np.take(env[ins[0]], env[ins[1]].astype(int),
                                          axis=_signed(at.get("axis", 0))),
        "ReduceSum": lambda ins, at: np.sum(
            env[ins[0]], axis=tuple(env[ins[1]].astype(int)),
            keepdims=bool(at.get("keepdims", 1))),
        "ReduceMax": lambda ins, at: np.max(
            env[ins[0]], axis=tuple(_signed(a) for a in at["axes"]),
            keepdims=bool(at.get("keepdims", 1))),
        "Einsum": lambda ins, at: np.einsum(at["equation"],
                                            *[env[i] for i in ins]),
        "Identity": lambda ins, at: env[ins[0]],
    }
    for node in graph["nodes"]:
        fn = ops.get(node["op_type"])
        assert fn is not None, f"interpreter missing {node['op_type']}"
        env[node["outputs"][0]] = fn(node["inputs"], node["attrs"])
    return [env[o] for o in graph["outputs"]]


def _signed(v):
    # protobuf varints are unsigned; attrs like axis=-1 decode as 2^64-1
    return v - (1 << 64) if v >= (1 << 63) else v


def _erf(x):
    # Abramowitz-Stegun 7.1.26 (enough for test tolerance)
    t = 1.0 / (1.0 + 0.3275911 * np.abs(x))
    y = 1 - (((((1.061405429 * t - 1.453152027) * t) + 1.421413741) * t
              - 0.284496736) * t + 0.254829592) * t * np.exp(-x * x)
    return np.sign(x) * y


def test_mlp_export_roundtrip(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                          nn.LayerNorm(16), nn.Linear(16, 4))
    path = export(model, str(tmp_path / "mlp"),
                  input_spec=[InputSpec([2, 8], "float32")])
    m = _decode_model(open(path, "rb").read())
    assert m["opset"] == 13
    g = m["graph"]
    assert g["inputs"] == ["input_0"]
    assert len(g["outputs"]) == 1
    ops = [n["op_type"] for n in g["nodes"]]
    assert "MatMul" in ops
    x = np.random.RandomState(0).randn(2, 8).astype("float32")
    (got,) = _run_graph(g, {"input_0": x})
    want = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_embedding_gelu_export(tmp_path):
    paddle.seed(1)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(32, 8)
            self.fc = nn.Linear(8, 8)

        def forward(self, ids):
            return nn.functional.gelu(self.fc(self.emb(ids)))

    model = Net()
    path = export(model, str(tmp_path / "emb"),
                  input_spec=[InputSpec([2, 5], "int32")])
    g = _decode_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Gather" in ops
    ids = np.array([[1, 5, 9, 0, 31], [2, 2, 7, 30, 4]], np.int32)
    (got,) = _run_graph(g, {"input_0": ids})
    want = model(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_conv_export_structure(tmp_path):
    paddle.seed(2)
    model = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1), nn.ReLU(),
                          nn.MaxPool2D(2, 2), nn.Flatten(),
                          nn.Linear(4 * 4 * 4, 5))
    path = export(model, str(tmp_path / "cnn"),
                  input_spec=[InputSpec([1, 3, 8, 8], "float32")])
    g = _decode_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Conv" in ops and "MaxPool" in ops
    conv = next(n for n in g["nodes"] if n["op_type"] == "Conv")
    assert conv["attrs"]["kernel_shape"] == [3, 3]
    assert conv["attrs"]["strides"] == [1, 1]
    # params are carried as initializers (weight + bias per layer)
    assert len(g["initializers"]) >= 4


def test_unsupported_primitive_raises(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=1)

    with pytest.raises(NotImplementedError, match="cumsum|unsupported"):
        export(Weird(), str(tmp_path / "weird"),
               input_spec=[InputSpec([2, 4], "float32")])


def test_channels_last_pool_export(tmp_path):
    """NHWC pooling must transpose around the ONNX pool op (which always
    pools trailing dims) — exported graph matches the traced model."""
    from paddle_tpu import nn as pnn

    prev = pnn.set_channels_last(True)
    try:
        paddle.seed(4)
        model = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1),
                              nn.AvgPool2D(2, 2))
        path = export(model, str(tmp_path / "nhwc"),
                      input_spec=[InputSpec([1, 8, 8, 3], "float32")])
    finally:
        pnn.set_channels_last(prev)
    g = _decode_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "AveragePool" in ops
    pool_i = ops.index("AveragePool")
    # pool is wrapped in the layout transposes
    assert ops[pool_i - 1] == "Transpose" and ops[pool_i + 1] == "Transpose"
