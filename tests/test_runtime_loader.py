"""Native C++ token loader vs python fallback."""
import os
import time

import numpy as np
import pytest

from paddle_tpu.runtime import NativeTokenLoader, PyTokenLoader, native_available


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "tokens.bin"
    arr = np.arange(100_000, dtype=np.int32) % 5000
    arr.tofile(path)
    return str(path)


def test_python_loader(token_file):
    ld = PyTokenLoader(token_file, batch_size=4, seq_len=16, seed=0)
    assert ld.num_tokens == 100_000
    b = ld.next()
    assert b.shape == (4, 17)
    # windows are contiguous slices of the arange stream
    diffs = np.diff(b.astype(np.int64), axis=1) % 5000
    assert ((diffs == 1) | (diffs == 1 - 5000 % 5000)).all()


@pytest.mark.skipif(not native_available(), reason="g++ toolchain unavailable")
def test_native_loader_correctness(token_file):
    ld = NativeTokenLoader(token_file, batch_size=8, seq_len=32, num_workers=2, seed=7)
    assert ld.num_tokens == 100_000
    for _ in range(5):
        b = ld.next()
        assert b.shape == (8, 33)
        assert b.min() >= 0 and b.max() < 5000
        # contiguity check (arange mod stream)
        d = np.diff(b.astype(np.int64), axis=1)
        assert np.isin(d, [1, 1 - 5000]).all()
    ld.close()


@pytest.mark.skipif(not native_available(), reason="g++ toolchain unavailable")
def test_native_loader_prefetch_throughput(token_file):
    ld = NativeTokenLoader(token_file, batch_size=32, seq_len=128,
                           num_workers=4, prefetch_depth=8, seed=1)
    t0 = time.time()
    for _ in range(50):
        ld.next()
    dt = time.time() - t0
    ld.close()
    assert dt < 5.0  # 50 batches of 32x129 ints should be near-instant
