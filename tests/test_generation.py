"""KV-cache decode correctness: incremental == full forward; sampling runs."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import GPT, GPTConfig
from paddle_tpu.models.generation import generate


def _cfg():
    return GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                     max_seq_len=64, dtype="float32", remat=False)


def test_cached_forward_matches_full():
    paddle.seed(0)
    model = GPT(_cfg())
    model.eval()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 10)).astype("int32"))
    full_logits = model(ids)
    # prefill through cache path
    cache = model.init_cache(2, 16)
    cached_logits, cache = model(ids, cache=cache, pos=0)
    np.testing.assert_allclose(cached_logits.numpy(), full_logits.numpy(),
                               rtol=1e-4, atol=1e-4)
    # one incremental step == full forward on the extended sequence
    nxt = paddle.to_tensor(np.array([[5], [7]], "int32"))
    step_logits, cache = model(nxt, cache=cache, pos=10)
    import jax.numpy as jnp
    ext = paddle.to_tensor(np.concatenate([ids.numpy(), nxt.numpy()], 1))
    full_ext = model(ext)
    np.testing.assert_allclose(step_logits.numpy()[:, 0], full_ext.numpy()[:, -1],
                               rtol=1e-4, atol=1e-4)


def test_greedy_generation_deterministic():
    paddle.seed(0)
    model = GPT(_cfg())
    ids = np.random.RandomState(1).randint(0, 128, (2, 8)).astype("int32")
    out1 = generate(model, ids, max_new_tokens=6, temperature=0.0)
    out2 = generate(model, ids, max_new_tokens=6, temperature=0.0)
    assert out1.shape == [2, 14]
    np.testing.assert_array_equal(out1.numpy(), out2.numpy())


def test_sampling_topk():
    paddle.seed(0)
    model = GPT(_cfg())
    ids = np.zeros((1, 4), "int32")
    out = generate(model, ids, max_new_tokens=5, temperature=0.8, top_k=10, seed=3)
    assert out.shape == [1, 9]
    assert out.numpy().max() < 128
