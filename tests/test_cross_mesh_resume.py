"""Cross-topology checkpoint resume: a run saved under one mesh shape
must resume under a different one (elastic restarts rarely get the same
topology back — e.g. dp2*fsdp4 preemption resumes as pure dp8).

Trainer.state() snapshots numpy leaves and load_state re-device_puts
them with the NEW trainer's shardings, so the checkpoint itself is
topology-free; this pins that property end-to-end by matching an
uninterrupted control run step-for-step.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.trainer import Trainer
from paddle_tpu.incubate.checkpoint import CheckpointManager

TOTAL, CUT = 6, 3


def _batches():
    rng = np.random.RandomState(42)
    return [{"x": rng.randn(8, 16).astype("float32"),
             "y": rng.randn(8, 4).astype("float32")}
            for _ in range(TOTAL)]


def _build():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.Tanh(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.Adam(learning_rate=0.05)

    def loss_fn(m, b):
        return paddle.nn.functional.mse_loss(
            m(paddle.to_tensor(b["x"])), paddle.to_tensor(b["y"]))

    return Trainer(net, opt, loss_fn)


def test_resume_on_different_mesh_topology(tmp_path):
    batches = _batches()

    # control: uninterrupted run on the RESUME topology (pure dp8)
    build_mesh(dp=8)
    tr = _build()
    control = [float(tr.step(b)) for b in batches]

    # interrupted run on dp2 x fsdp4 (params sharded over fsdp), saved at CUT
    build_mesh(dp=2, fsdp=4)
    tr = _build()
    first = [float(tr.step(b)) for b in batches[:CUT]]
    assert np.allclose(first, control[:CUT], rtol=1e-5, atol=1e-6)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), async_save=False)
    mgr.save(CUT, tr.state())
    mgr.wait_until_finished()

    # resume on pure dp8: fresh model, restore with the NEW trainer's
    # template so every leaf lands with the new mesh's sharding
    build_mesh(dp=8)
    tr = _build()
    state = mgr.restore_latest(template=tr.state())
    tr.load_state(state)
    assert tr._host_step == CUT
    rest = [float(tr.step(b)) for b in batches[CUT:]]
    assert np.allclose(rest, control[CUT:], rtol=1e-5, atol=1e-6), \
        (rest, control[CUT:])


def test_resume_into_sharded_topology(tmp_path):
    """The reverse direction: saved from pure dp8, resumed under
    dp2 x fsdp4 (replicated snapshot lands fsdp-sharded)."""
    batches = _batches()

    build_mesh(dp=2, fsdp=4)
    tr = _build()
    control = [float(tr.step(b)) for b in batches]

    build_mesh(dp=8)
    tr = _build()
    for b in batches[:CUT]:
        tr.step(b)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), async_save=False)
    mgr.save(CUT, tr.state())
    mgr.wait_until_finished()

    build_mesh(dp=2, fsdp=4)
    tr = _build()
    tr.load_state(mgr.restore_latest(template=tr.state()))
    rest = [float(tr.step(b)) for b in batches[CUT:]]
    assert np.allclose(rest, control[CUT:], rtol=1e-5, atol=1e-6), \
        (rest, control[CUT:])
