"""Campaign harness logic, offline: stage markers are written only when
at least one trial succeeded, banked trials skip on retry (a wedge
mid-sweep resumes at the trial it cut short), and one trial's failure
never aborts the stage.  bench is monkeypatched — no device."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))

import perf_campaign as pc  # noqa: E402


@pytest.fixture
def campaign_dir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _rows(path="perf_campaign_results.jsonl"):
    if not os.path.exists(path):
        return []
    return [json.loads(l) for l in open(path)]


def test_banked_skips_only_successful_matching_trials(campaign_dir):
    pc.record({"config": "yolov3", "bs": 16, "size": 320, "mfu": 0.3})
    pc.record({"config": "yolov3", "bs": 32, "size": 320,
               "error": "Wedge: ..."})
    assert pc.banked(config="yolov3", bs=16, size=320)
    assert not pc.banked(config="yolov3", bs=32, size=320)  # errored
    assert not pc.banked(config="yolov3", bs=16, size=416)  # never ran
    # r4-era gpt rows carry no accum key; accum=1 matches them
    pc.record({"config": "gpt_1p3b", "bs": 6, "remat": "dots", "mfu": 0.64})
    assert pc.banked(config="gpt_1p3b", bs=6, remat="dots")


def test_ocr_stage_marker_independent_of_yolo(campaign_dir, monkeypatch):
    """A crnn wedge must not be hidden behind yolo's success marker."""
    import bench

    monkeypatch.setattr(bench, "run_yolov3",
                        lambda batch_size, size: (100.0, 0.4))

    def crnn_fails(batch_size):
        raise RuntimeError("wedge")
    monkeypatch.setattr(bench, "run_crnn", crnn_fails)

    pc.run_yolo()
    pc.run_ocr()
    rows = _rows()
    assert any(r.get("config") == "yolo_stage_done" for r in rows)
    assert not any(r.get("config") == "ocr_stage_done" for r in rows)
    assert sum("error" in r and r["config"] == "crnn" for r in rows) == 2

    # crnn recovers on retry: marker appears, yolo trials all skip
    calls = {"n": 0}

    def yolo_counts(batch_size, size):
        calls["n"] += 1
        return (100.0, 0.4)
    monkeypatch.setattr(bench, "run_yolov3", yolo_counts)
    monkeypatch.setattr(bench, "run_crnn", lambda batch_size: (500.0, 0.2))
    pc.run_yolo()
    pc.run_ocr()
    assert calls["n"] == 0                  # everything banked
    assert any(r.get("config") == "ocr_stage_done" for r in _rows())


def test_gpt_stage_resumes_past_banked_trials(campaign_dir, monkeypatch):
    import bench

    pc.record({"config": "gpt_1p3b", "bs": 4, "remat": "dots",
               "tok_s": 15567.6, "mfu": 0.623})
    pc.record({"config": "gpt_1p3b", "bs": 6, "remat": "dots",
               "tok_s": 16027.8, "mfu": 0.6414})
    ran = []

    def fake_run_config(name, bs, seq, remat_policy=None, grad_accum=1):
        ran.append((bs, remat_policy, grad_accum))
        return 16000.0, 0.64, 1.3e9, 0
    monkeypatch.setattr(bench, "run_config", fake_run_config)
    pc.run_gpt(exhaustive=True)
    # banked bs4/bs6 skipped; new accum2 + wedge-quarantined configs
    # run, bs8 last
    assert ran == [(6, "dots", 2), (7, "dots", 1), (8, "dots", 2),
                   (8, "full", 1)]
    assert any(r.get("config") == "gpt_stage_done" for r in _rows())
    # retry: the accum2 rows now banked (matched WITH the accum key)
    ran.clear()
    pc.run_gpt(exhaustive=True)
    assert ran == []


# the advisor's real static ranking of GPT_GRID (verified at full scale
# by tests/test_remat_advisor.py::test_rank_gpt_1p3b_matches_measured_best
# and the committed docs/performance.md table): bs6/dots first
_ADVISOR_TOP2 = [("gpt_1p3b", 6, "dots", 1), ("gpt_1p3b", 4, "dots", 1)]


def _autotune_module():
    # `paddle_tpu.analysis.autotune` the ATTRIBUTE is the re-exported
    # function (package __init__ shadows the submodule); fetch the
    # module itself for monkeypatching
    import importlib
    return importlib.import_module("paddle_tpu.analysis.autotune")


@pytest.fixture
def static_advisor(monkeypatch):
    """Pin the advisor's selection so the plumbing test doesn't trace
    1.3B probes inside tier-1 (the ranking itself has its own tests)."""
    monkeypatch.setattr(_autotune_module(), "rank_gpt_candidates",
                        lambda grid, top=2, **kw: list(_ADVISOR_TOP2)[:top])


def test_advisor_measures_at_most_half_the_grid(campaign_dir,
                                                static_advisor,
                                                monkeypatch):
    """Acceptance: the advisor-gated gpt stage measures <= half the
    candidate grid and reports the same best config as --exhaustive on
    the same cached results."""
    import bench

    ran = []

    def fake_run_config(name, bs, seq, remat_policy=None, grad_accum=1):
        ran.append((bs, remat_policy, grad_accum))
        mfu = {(6, "dots", 1): 0.6414, (4, "dots", 1): 0.623}.get(
            (bs, remat_policy, grad_accum), 0.5)
        return mfu * 25000, mfu, 1.3e9, 0
    monkeypatch.setattr(bench, "run_config", fake_run_config)

    pc.run_gpt()                       # advisor mode (default)
    assert len(ran) == 2 <= len(pc.GPT_GRID) // 2
    assert set(ran) == {(6, "dots", 1), (4, "dots", 1)}
    best_advisor = pc.best_gpt_config()
    assert (best_advisor["bs"], best_advisor["remat"]) == (6, "dots")

    # exhaustive on the SAME results file: measures the rest, best
    # config unchanged
    ran.clear()
    pc.run_gpt(exhaustive=True)
    assert len(ran) == len(pc.GPT_GRID) - 2   # advisor's picks banked
    best_full = pc.best_gpt_config()
    assert (best_full["bs"], best_full["remat"]) == \
        (best_advisor["bs"], best_advisor["remat"])


def test_advisor_all_banked_widens_to_full_grid(campaign_dir,
                                                static_advisor,
                                                monkeypatch):
    """A repeat advisor-mode run whose top-2 are already banked widens
    to the full grid — the other 4 points stay reachable without the
    operator having to know about --exhaustive."""
    import bench

    ran = []
    monkeypatch.setattr(
        bench, "run_config",
        lambda name, bs, seq, remat_policy=None, grad_accum=1:
        ran.append((bs, remat_policy, grad_accum)) or (1.0, 0.1, 1, 0))
    pc.run_gpt()                                # measures the top-2
    assert len(ran) == 2
    ran.clear()
    pc.run_gpt()                                # top-2 banked -> widen
    assert len(ran) == len(pc.GPT_GRID) - 2
    ran.clear()
    pc.run_gpt()                                # everything banked now
    assert ran == []


def test_advisor_failure_falls_back_to_full_grid(campaign_dir,
                                                 monkeypatch):
    import bench

    def boom(*a, **kw):
        raise RuntimeError("probe exploded")
    monkeypatch.setattr(_autotune_module(), "rank_gpt_candidates", boom)
    ran = []
    monkeypatch.setattr(
        bench, "run_config",
        lambda name, bs, seq, remat_policy=None, grad_accum=1:
        ran.append((bs, remat_policy, grad_accum)) or (1.0, 0.1, 1, 0))
    pc.run_gpt()
    assert len(ran) == len(pc.GPT_GRID)


def test_all_errored_stage_stays_unbanked(campaign_dir, monkeypatch):
    import bench

    def always_fails(*a, **kw):
        raise RuntimeError("device init hung")
    monkeypatch.setattr(bench, "run_gpt_moe", always_fails)
    pc.run_moe()
    rows = _rows()
    assert not any(r.get("config") == "moe_stage_done" for r in rows)
    assert all("error" in r for r in rows if r.get("config") == "gpt_moe")


def test_banked_accum_defaults(campaign_dir):
    """accum-less r4 rows satisfy accum=1 queries; an accum=2 row must
    NOT satisfy an accum=1 query (else a wedged accum=1 trial is never
    retried once its accum=2 sibling lands)."""
    pc.record({"config": "gpt_1p3b", "bs": 6, "remat": "dots",
               "mfu": 0.64})                      # r4-era, no accum key
    pc.record({"config": "gpt_1p3b", "bs": 8, "remat": "dots",
               "accum": 2, "mfu": 0.6})
    d = {"accum": 1}
    assert pc.banked(config="gpt_1p3b", bs=6, remat="dots", accum=1,
                     _defaults=d)
    assert not pc.banked(config="gpt_1p3b", bs=8, remat="dots", accum=1,
                         _defaults=d)
    assert pc.banked(config="gpt_1p3b", bs=8, remat="dots", accum=2,
                     _defaults=d)
