"""Space-to-depth stem conv (ops/space_to_depth.py): exact equivalence
with the plain 7x7/s2 stem — the MLPerf ResNet TPU rewrite."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.ops.space_to_depth import space_to_depth_stem_conv


def test_matches_plain_stem_conv_bitwise():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 40, 3).astype("float32"))
    w = jnp.asarray(rng.randn(16, 3, 7, 7).astype("float32") * 0.1)
    got = space_to_depth_stem_conv(x, w)
    ref = jax.lax.conv_general_dilated(
        x, jnp.transpose(w, (2, 3, 1, 0)), window_strides=(2, 2),
        padding=((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert got.shape == ref.shape == (2, 16, 20, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_resnet_stem_s2d_forward_and_grads_match():
    """Same weights, flag on/off -> identical logits; grads flow to the
    original conv1 weight through the rewritten path.

    `slow`: two full resnet18 builds + a grad trace — 51 s under full-
    suite load, the next-worst tier-1 entry after the PR-15 zigzag
    marks (docs/performance.md wall-clock table). The op-level bitwise
    equivalence below keeps the s2d rewrite tier-1-covered."""
    paddle.seed(0)
    build_mesh(dp=1)
    m_plain = paddle.vision.models.resnet18(num_classes=5,
                                            data_format="NHWC")
    m_s2d = paddle.vision.models.resnet18(num_classes=5,
                                          data_format="NHWC",
                                          stem_s2d=True)
    m_s2d.set_state_dict(m_plain.state_dict())
    for m in (m_plain, m_s2d):
        m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(2, 64, 64, 3).astype("float32"))
    np.testing.assert_allclose(m_s2d(x).numpy(), m_plain(x).numpy(),
                               rtol=1e-4, atol=1e-4)

    m_s2d.train()
    y = m_s2d(x)
    y.sum().backward()
    assert m_s2d.conv1.weight.grad is not None
    assert float(jnp.max(jnp.abs(m_s2d.conv1.weight.grad._value))) > 0


def test_s2d_requires_nhwc_and_even_dims():
    import pytest
    with pytest.raises(ValueError, match="NHWC"):
        paddle.vision.models.resnet18(data_format="NCHW", stem_s2d=True)
    with pytest.raises(AssertionError):
        space_to_depth_stem_conv(jnp.zeros((1, 7, 8, 3)),
                                 jnp.zeros((4, 3, 7, 7)))
