"""Post-training static quantization (A8W8) — calibration + int8 matmul."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, QuantizedLinearA8W8


def test_ptq_calibrate_convert_accuracy():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
    model.eval()
    rng = np.random.RandomState(0)
    calib = [rng.randn(4, 16).astype("float32") for _ in range(4)]
    x_test = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
    ref = model(x_test).numpy()

    ptq = PTQ(model, min_out_features=1)
    for b in calib:
        model(paddle.to_tensor(b))
    assert ptq._amax and all(v > 0 for v in ptq._amax.values())
    model = ptq.convert()
    kinds = [type(m).__name__ for _, m in model.named_sublayers()]
    assert kinds.count("QuantizedLinearA8W8") == 2
    got = model(x_test).numpy()
    # int8 PTQ keeps outputs close on well-scaled data
    err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert err < 0.08, err


def test_a8w8_kernel_matches_manual():
    paddle.seed(1)
    lin = nn.Linear(8, 32)
    rng = np.random.RandomState(1)
    x = rng.randn(4, 8).astype("float32")
    act_scale = np.abs(x).max() / 127.0
    q = QuantizedLinearA8W8(lin, act_scale)
    got = q(paddle.to_tensor(x)).numpy()

    import jax.numpy as jnp
    w = lin.weight.numpy()
    amax = np.abs(w).max(axis=0, keepdims=True)
    sw = np.maximum(amax / 127.0, 1e-8)
    qw = np.clip(np.round(w / sw), -127, 127).astype(np.int8)
    qx = np.clip(np.round(x / act_scale), -127, 127).astype(np.int8)
    want = (qx.astype(np.int32) @ qw.astype(np.int32)).astype(np.float32) \
        * (sw * act_scale) + lin.bias.numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_hooks_removed_after_convert():
    paddle.seed(2)
    model = nn.Sequential(nn.Linear(8, 32))
    ptq = PTQ(model)
    model(paddle.to_tensor(np.ones((2, 8), np.float32)))
    before = dict(ptq._amax)
    ptq.convert()
    # further forwards must not touch the collector
    model(paddle.to_tensor(np.full((2, 8), 100.0, np.float32)))
    assert ptq._amax == before
