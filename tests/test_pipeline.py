"""Pipeline parallelism: GPipe schedule over 'pp' on the virtual mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.pipeline import pipeline_apply
from paddle_tpu.distributed.trainer import Trainer
from paddle_tpu.models import GPTConfig, GPTPretrainingCriterion, GPTStacked


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_apply_matches_sequential(schedule):
    build_mesh(pp=4)
    L_total, B, H = 8, 4, 16
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(L_total, H, H) * 0.1, jnp.float32)

    def stage_fn(params, x):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        out, _ = jax.lax.scan(body, x, params)
        return out

    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    seq = stage_fn(w, x)
    piped = pipeline_apply(stage_fn, w, x, n_microbatch=2, schedule=schedule)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq), atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_grads_match(schedule):
    build_mesh(pp=2)
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(4, 8, 8) * 0.1, jnp.float32)
    x = jnp.asarray(rng.randn(4, 8), jnp.float32)

    def stage_fn(params, xv):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        out, _ = jax.lax.scan(body, xv, params)
        return out

    def loss_seq(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def loss_pipe(w):
        return jnp.sum(pipeline_apply(stage_fn, w, x, n_microbatch=2,
                                      schedule=schedule) ** 2)

    g1 = jax.grad(loss_seq)(w)
    g2 = jax.grad(loss_pipe)(w)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1), atol=1e-4)


def _cfg():
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                     max_seq_len=32, dtype="float32", remat=True)


def _batch(bs=4, L=16, vocab=256):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (bs, L + 1))
    return {"input_ids": ids[:, :-1].astype("int32"),
            "labels": ids[:, 1:].astype("int32")}


def _loss_fn(model, batch):
    logits = model(paddle.to_tensor(batch["input_ids"]))
    return GPTPretrainingCriterion()(logits, paddle.to_tensor(batch["labels"]))


@pytest.mark.parametrize("schedule", [
    "1f1b", pytest.param("gpipe", marks=pytest.mark.slow),
    pytest.param("interleaved", marks=pytest.mark.slow),
    "interleaved_1f1b"])
def test_gpt_stacked_pp_equals_pp1(schedule):
    batch = _batch()
    losses = {}
    # pp x tp combined is covered by test_gpt_stacked_trains; comparing
    # dp1 vs pp4 here keeps one Trainer compile off the default suite
    pp = 2 if schedule.startswith("interleaved") else 4  # 4 layers = pp2 x v2
    for axes in ({"dp": 1}, {"pp": pp}):
        paddle.seed(11)
        build_mesh(**axes)
        model = GPTStacked(_cfg(), pp_microbatches=2, pp_schedule=schedule)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        trainer = Trainer(model, opt, _loss_fn)
        losses[tuple(sorted(axes.items()))] = [float(trainer.step(batch)) for _ in range(3)]
    vals = list(losses.values())
    np.testing.assert_allclose(vals[0], vals[1], rtol=1e-3)


@pytest.mark.parametrize("schedule", [
    "1f1b", pytest.param("interleaved", marks=pytest.mark.slow)])
def test_gpt_stacked_trains(schedule):
    paddle.seed(0)
    build_mesh(pp=2, dp=2, tp=2)
    model = GPTStacked(_cfg(), pp_microbatches=2, pp_schedule=schedule)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    trainer = Trainer(model, opt, _loss_fn)
    batch = _batch()
    losses = [float(trainer.step(batch)) for _ in range(8)]
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("schedule,pp", [
    ("interleaved", 2),
    ("interleaved_1f1b", 2),
    ("interleaved_1f1b", 4),     # pp4 x V2: the composed-schedule shape
])
def test_pipeline_interleaved_matches_sequential(schedule, pp):
    build_mesh(pp=pp)
    L_total, B, H, V = 8, 4, 16, 2
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(L_total, H, H) * 0.1, jnp.float32)

    def stage_fn(params, x):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        out, _ = jax.lax.scan(body, x, params)
        return out

    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    seq = stage_fn(w, x)
    piped = pipeline_apply(stage_fn, w, x, n_microbatch=4,
                           schedule=schedule, virtual=V)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq), atol=1e-5)

    def loss_seq(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def loss_pipe(w):
        return jnp.sum(pipeline_apply(stage_fn, w, x, n_microbatch=4,
                                      schedule=schedule, virtual=V) ** 2)

    g1 = jax.grad(loss_seq)(w)
    g2 = jax.grad(loss_pipe)(w)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1), atol=1e-4)


def test_interleaved_schedule_bubble_shrinks():
    """The virtual-stage schedule's fill bubble is ~(S-1) CHUNK ticks, so
    total chunk-ticks beat the non-interleaved equivalent V*(M+S-1)."""
    from paddle_tpu.distributed.pipeline import interleaved_schedule_table

    for (M, S, V) in [(4, 2, 2), (8, 4, 2), (8, 2, 4)]:
        T, tbl = interleaved_schedule_table(M, S, V)
        assert T < V * (M + S - 1), (M, S, V, T)
        # every item computed exactly once
        assert tbl["work"].sum() == M * S * V
