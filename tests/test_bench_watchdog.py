"""Regression tests for bench.py's mid-run hang protection and evidence
banking — the machinery that converts a TPU-tunnel wedge into a recorded
error line instead of a silent hang (observed live in round 4: device
init answered, gpt bs8 compiled and stepped, then the measure loop never
returned).

These run on the CPU backend; nothing here touches a device.
"""
import json
import signal
import subprocess
import sys
import threading
import time

import pytest

import bench


class TestAlarm:
    def test_raises_on_simulated_wedge(self):
        t0 = time.time()
        with pytest.raises(TimeoutError, match="wedge-sim exceeded 1s"):
            with bench._alarm(1, "wedge-sim"):
                time.sleep(30)
        assert time.time() - t0 < 5

    def test_normal_exit_leaves_no_residual_alarm(self):
        with bench._alarm(5, "noop"):
            pass
        assert signal.alarm(0) == 0

    def test_nested_guard_restores_outer_budget(self):
        with bench._alarm(30, "outer"):
            with bench._alarm(2, "inner"):
                pass
            remaining = signal.alarm(0)  # read + disarm the outer
            assert 20 < remaining <= 30
        assert signal.alarm(0) == 0

    def test_off_main_thread_is_noop(self):
        ran = []

        def work():
            with bench._alarm(1, "thread"):
                ran.append(True)

        t = threading.Thread(target=work)
        t.start()
        t.join()
        assert ran == [True]

    def test_hard_exit_fires_when_signal_cannot_deliver(self):
        # a blocked C call never runs bytecode, so the SIGALRM TimeoutError
        # is never raised; the backup thread must print the best-so-far
        # JSON line and hard-exit with code 3
        code = (
            "import threading, bench\n"
            "bench._publish_partial({'metric': 'm', 'value': 1.0,"
            " 'unit': 'u', 'vs_baseline': 2.0})\n"
            "with bench._alarm(-59, 'c-blocked'):\n"  # thread fires at 1s
            "    threading.Event().wait()\n"
        )
        p = subprocess.run([sys.executable, "-c", code], cwd=bench.__file__.rsplit("/", 1)[0],
                           capture_output=True, text=True, timeout=60,
                           env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"})
        assert p.returncode == 3
        out = json.loads(p.stdout.strip().splitlines()[-1])
        assert out["value"] == 1.0
        assert "hard-wedged" in out["error"]


class TestRecordFailure:
    def test_builds_message_before_dropping_reference(self):
        extras = {}
        try:
            raise RuntimeError("boom-" + "x" * 500)
        except RuntimeError as e:
            bench._record_failure(extras, "k", "stage", e)
        assert extras["k"].startswith("RuntimeError: boom-")
        assert len(extras["k"]) <= 160


class TestDeviceProbe:
    def test_first_probe_hang_short_circuits(self, monkeypatch):
        """A probe HANG means the tunnel is down: no 4x45s retry burn
        (r5 spent ~11 min reaching the cached-campaign fallback)."""
        calls = []

        def hang(*a, **kw):
            calls.append(kw.get("timeout"))
            raise subprocess.TimeoutExpired(cmd="probe",
                                            timeout=kw.get("timeout"))
        monkeypatch.setattr(subprocess, "run", hang)
        monkeypatch.setattr(time, "sleep",
                            lambda s: pytest.fail("slept on a hang"))
        err = bench._device_watchdog(timeout_s=1)
        assert err is not None
        assert "fast-fail on first probe" in err
        assert calls == [1]          # exactly one probe, no retries

    def test_error_then_hang_short_circuits(self, monkeypatch):
        """A hang fast-fails on ANY probe, not just the first: an
        error-exit flap followed by a hang must not burn the remaining
        retry budget (each retry would hang the same 150s way)."""
        monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", "4")
        calls = []

        class P:
            returncode = 1
            stderr = "flap"

        def flap_then_hang(*a, **kw):
            calls.append(1)
            if len(calls) == 1:
                return P()
            raise subprocess.TimeoutExpired(cmd="probe",
                                            timeout=kw.get("timeout"))
        monkeypatch.setattr(subprocess, "run", flap_then_hang)
        slept = []
        monkeypatch.setattr(time, "sleep", lambda s: slept.append(s))
        err = bench._device_watchdog(timeout_s=1, backoff_s=5)
        assert len(calls) == 2 and slept == [5]   # one retry, then hang
        assert "fast-fail on probe 2 hang" in err

    def test_probe_timeout_env_is_honored(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "7")
        monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", "1")
        seen = []

        def hang(*a, **kw):
            seen.append(kw.get("timeout"))
            raise subprocess.TimeoutExpired(cmd="probe", timeout=7)
        monkeypatch.setattr(subprocess, "run", hang)
        err = bench._device_watchdog()
        assert seen == [7] and "hung >7s" in err

    def test_error_exits_still_retry(self, monkeypatch):
        """Nonzero-exit probes are transient flaps: the retry budget
        (env-tunable) still applies — only hangs fast-fail."""
        monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", "3")
        calls = []

        class P:
            returncode = 1
            stderr = "boom"

        monkeypatch.setattr(subprocess, "run",
                            lambda *a, **kw: calls.append(1) or P())
        slept = []
        monkeypatch.setattr(time, "sleep", lambda s: slept.append(s))
        err = bench._device_watchdog(backoff_s=5)
        assert len(calls) == 3 and slept == [5, 5]
        assert "after 3 attempts" in err

    def test_healthy_probe_returns_none(self, monkeypatch):
        class P:
            returncode = 0
            stderr = ""

        monkeypatch.setattr(subprocess, "run", lambda *a, **kw: P())
        assert bench._device_watchdog(timeout_s=5) is None


class TestCachedCampaign:
    def test_keeps_strongest_variants_not_most_recent(self, tmp_path):
        p = tmp_path / "sweep.jsonl"
        rows = [{"config": "resnet50", "bs": 128 * (1 + i % 3), "mfu": m}
                for i, m in enumerate([0.30, 0.26, 0.22, 0.20, 0.19, 0.18])]
        rows.append({"config": "resnet50", "bs": 512,
                     "error": "RESOURCE_EXHAUSTED"})
        rows.append({"config": "resnet_stage_done"})
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        cc = bench._cached_campaign(str(p))
        kept = [t["mfu"] for t in cc["results"]["resnet50"]]
        assert kept == [0.30, 0.26, 0.22]
        # error lines and stage markers are evidence-free — excluded
        assert all("error" not in t for t in cc["results"]["resnet50"])
        assert "resnet_stage_done" not in cc["results"]
        assert cc["recorded_at"].endswith("Z")

    def test_missing_file_returns_none(self, tmp_path):
        assert bench._cached_campaign(str(tmp_path / "absent.jsonl")) is None

    def test_no_mfu_falls_back_to_most_recent(self, tmp_path):
        p = tmp_path / "sweep.jsonl"
        rows = [{"config": "decode", "quant": q, "tok_s": 100 + i}
                for i, q in enumerate(["bf16", "a8w8", "w4a16", "x", "y"])]
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        cc = bench._cached_campaign(str(p))
        assert [t["tok_s"] for t in cc["results"]["decode"]] == [102, 103, 104]
