"""Regression tests for bench.py's mid-run hang protection and evidence
banking — the machinery that converts a TPU-tunnel wedge into a recorded
error line instead of a silent hang (observed live in round 4: device
init answered, gpt bs8 compiled and stepped, then the measure loop never
returned).

These run on the CPU backend; nothing here touches a device.
"""
import json
import signal
import subprocess
import sys
import threading
import time

import pytest

import bench


class TestAlarm:
    def test_raises_on_simulated_wedge(self):
        t0 = time.time()
        with pytest.raises(TimeoutError, match="wedge-sim exceeded 1s"):
            with bench._alarm(1, "wedge-sim"):
                time.sleep(30)
        assert time.time() - t0 < 5

    def test_normal_exit_leaves_no_residual_alarm(self):
        with bench._alarm(5, "noop"):
            pass
        assert signal.alarm(0) == 0

    def test_nested_guard_restores_outer_budget(self):
        with bench._alarm(30, "outer"):
            with bench._alarm(2, "inner"):
                pass
            remaining = signal.alarm(0)  # read + disarm the outer
            assert 20 < remaining <= 30
        assert signal.alarm(0) == 0

    def test_off_main_thread_is_noop(self):
        ran = []

        def work():
            with bench._alarm(1, "thread"):
                ran.append(True)

        t = threading.Thread(target=work)
        t.start()
        t.join()
        assert ran == [True]

    def test_hard_exit_fires_when_signal_cannot_deliver(self):
        # a blocked C call never runs bytecode, so the SIGALRM TimeoutError
        # is never raised; the backup thread must print the best-so-far
        # JSON line and hard-exit with code 3
        code = (
            "import threading, bench\n"
            "bench._publish_partial({'metric': 'm', 'value': 1.0,"
            " 'unit': 'u', 'vs_baseline': 2.0})\n"
            "with bench._alarm(-59, 'c-blocked'):\n"  # thread fires at 1s
            "    threading.Event().wait()\n"
        )
        p = subprocess.run([sys.executable, "-c", code], cwd=bench.__file__.rsplit("/", 1)[0],
                           capture_output=True, text=True, timeout=60,
                           env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"})
        assert p.returncode == 3
        out = json.loads(p.stdout.strip().splitlines()[-1])
        assert out["value"] == 1.0
        assert "hard-wedged" in out["error"]


class TestRecordFailure:
    def test_builds_message_before_dropping_reference(self):
        extras = {}
        try:
            raise RuntimeError("boom-" + "x" * 500)
        except RuntimeError as e:
            bench._record_failure(extras, "k", "stage", e)
        assert extras["k"].startswith("RuntimeError: boom-")
        assert len(extras["k"]) <= 160


class TestCachedCampaign:
    def test_keeps_strongest_variants_not_most_recent(self, tmp_path):
        p = tmp_path / "sweep.jsonl"
        rows = [{"config": "resnet50", "bs": 128 * (1 + i % 3), "mfu": m}
                for i, m in enumerate([0.30, 0.26, 0.22, 0.20, 0.19, 0.18])]
        rows.append({"config": "resnet50", "bs": 512,
                     "error": "RESOURCE_EXHAUSTED"})
        rows.append({"config": "resnet_stage_done"})
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        cc = bench._cached_campaign(str(p))
        kept = [t["mfu"] for t in cc["results"]["resnet50"]]
        assert kept == [0.30, 0.26, 0.22]
        # error lines and stage markers are evidence-free — excluded
        assert all("error" not in t for t in cc["results"]["resnet50"])
        assert "resnet_stage_done" not in cc["results"]
        assert cc["recorded_at"].endswith("Z")

    def test_missing_file_returns_none(self, tmp_path):
        assert bench._cached_campaign(str(tmp_path / "absent.jsonl")) is None

    def test_no_mfu_falls_back_to_most_recent(self, tmp_path):
        p = tmp_path / "sweep.jsonl"
        rows = [{"config": "decode", "quant": q, "tok_s": 100 + i}
                for i, q in enumerate(["bf16", "a8w8", "w4a16", "x", "y"])]
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        cc = bench._cached_campaign(str(p))
        assert [t["tok_s"] for t in cc["results"]["decode"]] == [102, 103, 104]
