"""Measuring profiler: real step/event/op timings, exportable trace.

Reference: python/paddle/profiler/profiler.py + timer.py.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler as prof_mod
from paddle_tpu.profiler import Profiler, RecordEvent, make_scheduler, ProfilerState


def test_profiler_measures_steps_events_ops(tmp_path):
    paddle.seed(0)
    lin = paddle.nn.Linear(32, 32)
    x = paddle.randn([8, 32])
    p = Profiler(timer_only=False, log_dir=str(tmp_path), record_ops=True)
    p.start()
    for i in range(3):
        with RecordEvent("fwd"):
            y = lin(x)
            loss = (y * y).mean()
        p.step(num_samples=8)
    p.stop()

    s = p.summary()
    assert "train_step" in s
    assert "fwd" in s
    # op table has measured, nonzero host times
    assert "Ops (eager dispatch, host)" in s
    op_totals = [st.total for st in p._op_stats.values()]
    assert op_totals and all(t > 0 for t in op_totals)
    assert p._step_stat.count == 3
    assert p._step_stat.total > 0
    assert "ips" in p.step_info()

    path = p.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        data = json.load(f)
    assert data["traceEvents"], "exported timeline is empty"
    names = {e["name"] for e in data["traceEvents"]}
    assert "fwd" in names
    loaded = prof_mod.load_profiler_result(path)
    assert loaded["traceEvents"]


def test_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED


def test_profiler_op_hook_removed_after_stop():
    from paddle_tpu.framework import core
    p = Profiler(timer_only=False, record_ops=True, log_dir="/tmp/_prof_x")
    p.start()
    assert core._op_profiler is p
    p.stop()
    assert core._op_profiler is None


def test_chrome_trace_round_trip_preserves_nesting(tmp_path):
    """export_chrome_tracing -> load_profiler_result round-trip:
    RecordEvent region names, timestamps and NESTING survive — a child
    region's exported interval sits inside its parent's, the exported
    events are ts-sorted per track (the Perfetto render contract the
    serving.trace schema gate checks), and durations match what the
    profiler measured."""
    import time as _time

    from paddle_tpu.profiler import export_chrome_tracing, load_profiler_result
    from paddle_tpu.serving import validate_chrome_trace

    paths = []
    handler = export_chrome_tracing(str(tmp_path))
    p = Profiler(timer_only=True,
                 on_trace_ready=lambda prof: paths.append(handler(prof)))
    p.start()
    for _ in range(2):
        with RecordEvent("outer"):
            _time.sleep(0.002)
            with RecordEvent("inner"):
                _time.sleep(0.002)
            _time.sleep(0.001)
        p.step()
    p.stop()

    assert paths, "on_trace_ready never exported"
    loaded = load_profiler_result(paths[-1])
    events = loaded["traceEvents"]
    assert validate_chrome_trace(loaded) == []
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["outer"]) == 2 and len(by_name["inner"]) == 2
    assert "step#0" in by_name and "step#1" in by_name
    # ts-sorted on the single host track
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    # nesting: each inner interval is CONTAINED in one outer interval
    for inner in by_name["inner"]:
        assert any(o["ts"] <= inner["ts"] and
                   inner["ts"] + inner["dur"] <= o["ts"] + o["dur"]
                   for o in by_name["outer"]), (inner, by_name["outer"])
    # measured durations survive the round-trip (us vs the stats table)
    stat = p._event_stats["inner"]
    total_us = sum(e["dur"] for e in by_name["inner"])
    assert total_us == pytest.approx(stat.total * 1e6, rel=1e-6)
    assert all(e["dur"] >= 2000 for e in by_name["inner"])   # >= sleep
