"""Measuring profiler: real step/event/op timings, exportable trace.

Reference: python/paddle/profiler/profiler.py + timer.py.
"""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler as prof_mod
from paddle_tpu.profiler import Profiler, RecordEvent, make_scheduler, ProfilerState


def test_profiler_measures_steps_events_ops(tmp_path):
    paddle.seed(0)
    lin = paddle.nn.Linear(32, 32)
    x = paddle.randn([8, 32])
    p = Profiler(timer_only=False, log_dir=str(tmp_path), record_ops=True)
    p.start()
    for i in range(3):
        with RecordEvent("fwd"):
            y = lin(x)
            loss = (y * y).mean()
        p.step(num_samples=8)
    p.stop()

    s = p.summary()
    assert "train_step" in s
    assert "fwd" in s
    # op table has measured, nonzero host times
    assert "Ops (eager dispatch, host)" in s
    op_totals = [st.total for st in p._op_stats.values()]
    assert op_totals and all(t > 0 for t in op_totals)
    assert p._step_stat.count == 3
    assert p._step_stat.total > 0
    assert "ips" in p.step_info()

    path = p.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        data = json.load(f)
    assert data["traceEvents"], "exported timeline is empty"
    names = {e["name"] for e in data["traceEvents"]}
    assert "fwd" in names
    loaded = prof_mod.load_profiler_result(path)
    assert loaded["traceEvents"]


def test_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED


def test_profiler_op_hook_removed_after_stop():
    from paddle_tpu.framework import core
    p = Profiler(timer_only=False, record_ops=True, log_dir="/tmp/_prof_x")
    p.start()
    assert core._op_profiler is p
    p.stop()
    assert core._op_profiler is None
