"""Chunked collective-matmul overlap (ops/overlap.py): every ring
decomposition must be BIT-IDENTICAL to its bulk-collective twin — the
whole contract that lets tp_overlap/dp_overlap default-off configs and
overlapped configs share golden outputs. Pins the degenerate shapes
(n_chunks=1, ragged tail chunk, 1-participant axis, indivisible free
dim) per dtype, the tp GPT block through the model wiring, and the
dp-overlapped Trainer's losses across seeds."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.trainer import Trainer
from paddle_tpu.models import GPT, GPTConfig, GPTPretrainingCriterion
from paddle_tpu.ops.overlap import (chunked_all_gather_matmul,
                                    chunked_all_reduce,
                                    chunked_matmul_all_reduce,
                                    chunked_matmul_reduce_scatter,
                                    overlap_all_gather_matmul,
                                    overlap_matmul_all_reduce,
                                    overlap_matmul_reduce_scatter)

P = 4   # tp participants; the virtual mesh has 8 devices


def tp_mesh(p=P):
    return build_mesh(tp=p, devices=jax.devices()[:p])


def _mats(m, k, n, dtype, seed=0):
    """GLOBAL operands for the row-parallel wrappers: x [m, P*k] (last
    dim tp-sharded), w [P*k, n] (first dim tp-sharded)."""
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(m, P * k), dtype)
    w = jnp.asarray(rng.randn(P * k, n), dtype)
    return x, w


def _bits(a):
    return np.asarray(a).tobytes()


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("n_chunks", [1, 3, 4])
def test_matmul_all_reduce_bit_identical(dtype, n_chunks):
    """ring == bulk psum twin, bit for bit — including n_chunks=1 (one
    tile IS the bulk matmul) and n_chunks=3 (ragged tail: 12 cols over
    4 devices -> 3-col dest blocks split 2/1... per chunk)."""
    mesh = tp_mesh()
    x, w = _mats(8, 16, 12, dtype)
    ring = jax.jit(lambda x, w: overlap_matmul_all_reduce(
        x, w, axis="tp", n_chunks=n_chunks, mesh=mesh, impl="ring"))
    bulk = jax.jit(lambda x, w: overlap_matmul_all_reduce(
        x, w, axis="tp", n_chunks=n_chunks, mesh=mesh, impl="bulk"))
    assert _bits(ring(x, w)) == _bits(bulk(x, w))


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_matmul_reduce_scatter_bit_identical(dtype):
    mesh = tp_mesh()
    x, w = _mats(8, 16, 16, dtype)
    for n_chunks in (1, 2, 4):
        ring = jax.jit(lambda x, w: overlap_matmul_reduce_scatter(
            x, w, axis="tp", n_chunks=n_chunks, mesh=mesh,
            impl="ring"))
        bulk = jax.jit(lambda x, w: overlap_matmul_reduce_scatter(
            x, w, axis="tp", n_chunks=n_chunks, mesh=mesh,
            impl="bulk"))
        assert _bits(ring(x, w)) == _bits(bulk(x, w))


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_all_gather_matmul_bit_identical(dtype):
    mesh = tp_mesh()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16), dtype)      # row-sharded over tp
    w = jnp.asarray(rng.randn(16, 12), dtype)
    for n_chunks in (1, 4, 7):
        ring = jax.jit(lambda x, w: overlap_all_gather_matmul(
            x, w, axis="tp", n_chunks=n_chunks, mesh=mesh, impl="ring"))
        bulk = jax.jit(lambda x, w: overlap_all_gather_matmul(
            x, w, axis="tp", n_chunks=n_chunks, mesh=mesh, impl="bulk"))
        assert _bits(ring(x, w)) == _bits(bulk(x, w))


def test_indivisible_free_dim_all_reduce():
    """N % p != 0: the all-reduce falls back to one bulk dot with a
    chunked exchange — still bit-identical to the psum twin."""
    mesh = tp_mesh()
    x, w = _mats(4, 16, 97, "float32")
    ring = jax.jit(lambda x, w: overlap_matmul_all_reduce(
        x, w, axis="tp", n_chunks=4, mesh=mesh, impl="ring"))
    bulk = jax.jit(lambda x, w: overlap_matmul_all_reduce(
        x, w, axis="tp", n_chunks=4, mesh=mesh, impl="bulk"))
    assert _bits(ring(x, w)) == _bits(bulk(x, w))


def test_reduce_scatter_raises_on_indivisible():
    mesh = tp_mesh()
    x, w = _mats(4, 16, 10, "float32")
    with pytest.raises(ValueError, match="divisible"):
        overlap_matmul_reduce_scatter(x, w, axis="tp", mesh=mesh)


def test_single_participant_axis_is_noop_zero_wire():
    """A 1-participant axis folds to the plain matmul: no collective
    primitive anywhere in the captured body — zero wire, not a
    degenerate ring of self-sends."""
    def body(x, w):
        return chunked_matmul_all_reduce(x, w, "tp", n_chunks=4)
    jx = jax.make_jaxpr(body, axis_env=[("tp", 1)])(
        jnp.zeros((4, 8), jnp.float32), jnp.zeros((8, 12), jnp.float32))

    def prims(j, acc):
        for e in j.eqns:
            acc.add(e.primitive.name)
            for v in e.params.values():
                if hasattr(v, "jaxpr"):
                    prims(v.jaxpr, acc)
        return acc
    names = prims(jx.jaxpr, set())
    assert "dot_general" in names
    assert not names & {"ppermute", "psum", "all_gather",
                        "reduce_scatter", "psum_scatter"}, names


def test_chunked_all_reduce_matches_psum():
    """The array twin (dp grad buckets ride this): full-exchange ring
    == lax.psum, f32 and bf16."""
    mesh = tp_mesh(8)
    from jax.sharding import PartitionSpec as Spec

    from paddle_tpu.distributed.mesh import compat_shard_map
    for dtype in ("float32", "bfloat16"):
        g = jnp.asarray(np.random.RandomState(3).randn(8, 5, 7), dtype)
        ring = compat_shard_map(
            lambda v: chunked_all_reduce(v[0], "tp"), mesh,
            in_specs=(Spec("tp"),), out_specs=Spec(),
            axis_names={"tp"}, check=False)
        ref = compat_shard_map(
            lambda v: jax.lax.psum(v[0], "tp"), mesh,
            in_specs=(Spec("tp"),), out_specs=Spec(),
            axis_names={"tp"}, check=False)
        assert _bits(jax.jit(ring)(g)) == _bits(jax.jit(ref)(g))


def _tiny_cfg(**kw):
    base = dict(vocab_size=256, hidden_size=64, num_layers=2,
                num_heads=4, max_seq_len=64, dtype="float32",
                remat=False)
    base.update(kw)
    return GPTConfig(**base)


def _batch(bs=8, L=16, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (bs, L + 1))
    return {"input_ids": ids[:, :-1].astype("int32"),
            "labels": ids[:, 1:].astype("int32")}


def _loss_fn(model, batch):
    logits = model(paddle.to_tensor(batch["input_ids"]))
    return GPTPretrainingCriterion()(logits,
                                     paddle.to_tensor(batch["labels"]))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_gpt_block_tp_overlap_bit_identical(seed):
    """The wired model path: a tp=4 GPT forward with tp_overlap='ring'
    is bit-identical to tp_overlap='bulk' (the GSPMD psum twin) —
    per seed, through embedding/attention/FFN/proj."""
    ids = _batch(bs=2, L=16, seed=seed)["input_ids"]
    logits = {}
    for impl in ("bulk", "ring"):
        paddle.seed(seed)
        tp_mesh()
        model = GPT(_tiny_cfg(tp_overlap=impl, tp_overlap_chunks=2))
        logits[impl] = np.asarray(
            model(paddle.to_tensor(ids))._value)
    assert _bits(logits["ring"]) == _bits(logits["bulk"])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_trainer_dp_overlap_bit_identical(seed):
    """dp=8 training with the bucketed chunked grad all-reduce: ring
    losses == bulk losses bit for bit over real AdamW steps."""
    losses = {}
    for impl in ("bulk", "ring"):
        paddle.seed(seed)
        build_mesh(dp=8)
        model = GPT(_tiny_cfg())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        tr = Trainer(model, opt, _loss_fn, dp_overlap=impl,
                     dp_overlap_buckets=3)
        losses[impl] = [float(tr.step(_batch(seed=seed)))
                        for _ in range(2)]
    assert losses["ring"] == losses["bulk"], losses


def test_trainer_dp_overlap_matches_gspmd_path():
    """The overlapped trainer trains the same model: losses allclose
    to the default GSPMD dp path (not bit-pinned — different reduction
    association by construction)."""
    runs = {}
    for mode in ("off", "ring"):
        paddle.seed(7)
        build_mesh(dp=8)
        model = GPT(_tiny_cfg())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        kw = {} if mode == "off" else dict(dp_overlap=mode,
                                           dp_overlap_buckets=2)
        tr = Trainer(model, opt, _loss_fn, **kw)
        runs[mode] = [float(tr.step(_batch())) for _ in range(2)]
    assert np.allclose(runs["off"], runs["ring"], rtol=1e-5)


def test_trainer_dp_overlap_rejects_grad_transform():
    paddle.seed(0)
    build_mesh(dp=8)
    model = GPT(_tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    with pytest.raises(ValueError, match="dp_overlap"):
        Trainer(model, opt, _loss_fn, dp_overlap="ring",
                grad_transform=lambda g: g)


def test_gpt_config_validates_tp_overlap():
    with pytest.raises(ValueError, match="tp_overlap"):
        _tiny_cfg(tp_overlap="nope")
