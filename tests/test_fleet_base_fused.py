"""Fleet base infra (topology/rolemaker/util/data generators),
FusedMultiTransformer, Bilinear initializer, Flowers/VOC2012 datasets —
reference fleet/base/*, incubate/nn/layer/fused_transformer.py:627,
nn/initializer/Bilinear, vision/datasets/{flowers,voc2012}.py."""
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet


def test_communicate_topology_roundtrip():
    topo = fleet.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
    assert topo.world_size() == 8
    for r in range(8):
        c = topo.get_coord(r)
        assert topo.get_rank(data=c.data, pipe=c.pipe, model=c.model) == r
    assert topo.get_dim("pipe") == 2
    # comm groups along 'model': 4 groups of 2 ranks, disjoint, covering all
    groups = topo.get_comm_list("model")
    assert len(groups) == 4 and all(len(g) == 2 for g in groups)
    assert sorted(sum(groups, [])) == list(range(8))
    assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]


def test_role_maker_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    rm = fleet.PaddleCloudRoleMaker()
    assert rm.worker_index() == 2 and rm.worker_num() == 4
    assert not rm.is_first_worker() and rm._is_worker()
    u = fleet.UtilBase()
    files = [f"part-{i}" for i in range(10)]
    shard = u.get_file_shard(files)
    # 10 files over 4 workers: sizes 3,3,2,2; worker 2 gets part-6, part-7
    assert shard == ["part-6", "part-7"]
    all_shards = []
    for i in range(4):
        monkeypatch.setenv("PADDLE_TRAINER_ID", str(i))
        all_shards += u.get_file_shard(files)
    assert all_shards == files


def test_multislot_data_generators():
    class G(fleet.MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def gen():
                w = line.split()
                yield [("words", w[:-1]), ("label", [w[-1]])]
            return gen

    g = G()
    g.set_batch(2)
    out = g.run_from_memory(["1926 08 17 1", "4 5 0"])
    assert out == ["3 1926 08 17 1 1\n", "2 4 5 1 0\n"]

    class GN(fleet.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("ids", [1, 2, 3]), ("label", [1])]
            return gen

    out = GN().run_from_memory(["x"])
    assert out == ["3 1 2 3 1 1\n"]


def test_fleet_class_surface():
    f = fleet.Fleet()
    assert f.is_worker() and not f.is_server()
    assert isinstance(f.util, fleet.UtilBase)
    assert f.worker_num() >= 1


def test_bilinear_initializer_upsamples():
    from paddle_tpu.nn.initializer import Bilinear
    w = np.asarray(Bilinear()([1, 1, 4, 4], "float32"))
    # symmetric stencil, peak at center block
    np.testing.assert_allclose(w[0, 0], w[0, 0][::-1, ::-1], atol=1e-6)
    assert w[0, 0, 1, 1] == w.max()
    # conv-transpose with this kernel interpolates a constant exactly
    ct = paddle.nn.Conv2DTranspose(
        1, 1, 4, stride=2, padding=1,
        weight_attr=paddle.ParamAttr(initializer=Bilinear()),
        bias_attr=False)
    x = paddle.to_tensor(np.ones((1, 1, 4, 4), "float32"))
    y = np.asarray(ct(x)._value)
    assert y.shape == (1, 1, 8, 8)
    np.testing.assert_allclose(y[0, 0, 2:-2, 2:-2], 1.0, atol=1e-5)


def test_fused_multi_transformer_decode_matches_full():
    """Cache-incremental decode reproduces the full-sequence forward —
    the layer's two execution paths agree (reference
    FusedMultiTransformer semantics)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    paddle.seed(0)
    L, B, T, h = 2, 2, 6, 32
    m = FusedMultiTransformer(embed_dim=h, num_heads=4, dim_feedforward=64,
                              num_layers=L, normalize_before=True)
    m.eval()
    rng = np.random.RandomState(0)
    x = rng.randn(B, T, h).astype("float32") * 0.3

    full = np.asarray(m(paddle.to_tensor(x))._value)
    assert full.shape == (B, T, h)

    caches = m.gen_cache(B, T)
    outs = []
    for t in range(T):
        o, caches = m(paddle.to_tensor(x[:, t:t + 1]), caches=caches,
                      time_step=t)
        outs.append(np.asarray(o._value))
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(inc, full, atol=2e-5)


def test_fused_multi_transformer_attrs_honored():
    """Per-layer ParamAttr initializers must take effect (reference
    FasterGPT weight-loading path)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.nn.initializer import Assign
    h = 8
    ws = [np.full((h, 3 * h), 0.1 * (i + 1), "float32") for i in range(2)]
    m = FusedMultiTransformer(
        embed_dim=h, num_heads=2, dim_feedforward=16, num_layers=2,
        qkv_weight_attrs=[paddle.ParamAttr(initializer=Assign(w))
                          for w in ws])
    got = np.asarray(m.qkv_weight.numpy())
    np.testing.assert_allclose(got, np.stack(ws))


def test_fleet_role_maker_delegation():
    f = fleet.Fleet()
    f.init(fleet.UserDefinedRoleMaker(current_id=3,
                                      worker_endpoints=["a"] * 4))
    assert f.worker_index() == 3 and f.worker_num() == 4


def test_fused_multi_transformer_post_ln_and_mask():
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    paddle.seed(1)
    m = FusedMultiTransformer(embed_dim=16, num_heads=2, dim_feedforward=32,
                              num_layers=2, normalize_before=False)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(1, 5, 16).astype("float32"))
    mask = paddle.to_tensor(np.tril(np.ones((5, 5), "float32")))
    out = m(x, attn_mask=mask)
    assert list(out.shape) == [1, 5, 16]
    assert np.all(np.isfinite(np.asarray(out._value)))


@pytest.fixture()
def flowers_archives(tmp_path):
    import scipy.io as scio
    from PIL import Image
    jpg_dir = tmp_path / "jpg"
    jpg_dir.mkdir()
    rng = np.random.RandomState(0)
    n = 6
    for i in range(1, n + 1):
        Image.fromarray(rng.randint(0, 255, (16, 16, 3), dtype=np.uint8)) \
            .save(str(jpg_dir / f"image_{i:05d}.jpg"))
    tgz = tmp_path / "102flowers.tgz"
    with tarfile.open(str(tgz), "w:gz") as t:
        for i in range(1, n + 1):
            t.add(str(jpg_dir / f"image_{i:05d}.jpg"),
                  arcname=f"jpg/image_{i:05d}.jpg")
    labels = tmp_path / "imagelabels.mat"
    scio.savemat(str(labels),
                 {"labels": np.arange(1, n + 1).reshape(1, -1)})
    setid = tmp_path / "setid.mat"
    scio.savemat(str(setid), {"trnid": np.array([[1, 2, 3, 4]]),
                              "valid": np.array([[5]]),
                              "tstid": np.array([[6]])})
    return str(tgz), str(labels), str(setid)


def test_flowers_dataset(flowers_archives):
    from paddle_tpu.vision.datasets import Flowers
    tgz, labels, setid = flowers_archives
    ds = Flowers(data_file=tgz, label_file=labels, setid_file=setid,
                 mode="train")
    assert len(ds) == 4
    img, lab = ds[0]
    assert img.shape == (16, 16, 3) and lab.tolist() == [1]
    assert len(Flowers(data_file=tgz, label_file=labels,
                       setid_file=setid, mode="test")) == 1
    with pytest.raises(ValueError, match="zero-egress"):
        Flowers(mode="train")


def test_voc2012_dataset(tmp_path):
    from PIL import Image
    from paddle_tpu.vision.datasets import VOC2012
    rng = np.random.RandomState(0)
    base = "VOCdevkit/VOC2012"
    root = tmp_path / "voc"
    for sub in ("JPEGImages", "SegmentationClass",
                "ImageSets/Segmentation"):
        (root / base / sub).mkdir(parents=True)
    names = ["2007_000032", "2007_000033"]
    for n in names:
        Image.fromarray(rng.randint(0, 255, (12, 12, 3), dtype=np.uint8)) \
            .save(str(root / base / "JPEGImages" / f"{n}.jpg"))
        Image.fromarray(rng.randint(0, 20, (12, 12), dtype=np.uint8)) \
            .save(str(root / base / "SegmentationClass" / f"{n}.png"))
    (root / base / "ImageSets/Segmentation/train.txt") \
        .write_text("\n".join(names))
    tar = tmp_path / "voctrainval.tar"
    with tarfile.open(str(tar), "w") as t:
        t.add(str(root / "VOCdevkit"), arcname="VOCdevkit")
    ds = VOC2012(data_file=str(tar), mode="train")
    assert len(ds) == 2
    img, lab = ds[0]
    assert img.shape == (12, 12, 3) and lab.shape == (12, 12)
