"""Jacobian/Hessian functional autograd — parity vs jax.jacrev/jacfwd and
reference semantics (python/paddle/autograd/functional.py:165,255,698+)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import (
    Hessian,
    Jacobian,
    batch_hessian,
    batch_jacobian,
    hessian,
    jacobian,
    vhp,
)


def test_jacobian_object_single_input():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
    J = Jacobian(lambda t: paddle.matmul(t, t), x)
    assert J.shape == (4, 4)
    full = np.asarray(J[:])
    golden = jax.jacrev(lambda a: (a @ a).reshape(-1))(x.numpy()).reshape(4, 4)
    np.testing.assert_allclose(full, np.asarray(golden), rtol=1e-5)
    # row indexing
    np.testing.assert_allclose(np.asarray(J[0, :]), np.asarray(golden)[0], rtol=1e-5)


def test_jacobian_object_multi_input_concat():
    # reference docstring example: func(x, y) = matmul(x, y), xs=[x, x]
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
    J = Jacobian(lambda a, b: paddle.matmul(a, b), [x, x])
    assert J.shape == (4, 8)
    expected_row0 = np.array([1., 3., 0., 0., 1., 0., 2., 0.], np.float32)
    np.testing.assert_allclose(np.asarray(J[0, :]), expected_row0, rtol=1e-5)


def test_jacobian_batched():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3).astype(np.float32)
    w = rng.randn(3, 5).astype(np.float32)
    J = Jacobian(lambda t: paddle.matmul(t, paddle.to_tensor(w)),
                 paddle.to_tensor(x), is_batched=True)
    assert J.shape == (4, 5, 3)
    full = np.asarray(J[:])
    for b in range(4):
        np.testing.assert_allclose(full[b], w.T, rtol=1e-5)


def test_hessian_object():
    rng = np.random.RandomState(1)
    x = rng.randn(6).astype(np.float32)
    a = rng.randn(6, 6).astype(np.float32)
    sym = (a + a.T) / 2

    def quad(t):
        return paddle.sum(paddle.matmul(t.reshape([1, 6]),
                                        paddle.matmul(paddle.to_tensor(sym), t.reshape([6, 1]))))

    H = Hessian(quad, paddle.to_tensor(x))
    assert H.shape == (6, 6)
    np.testing.assert_allclose(np.asarray(H[:]), 2 * sym, rtol=1e-4, atol=1e-5)


def test_hessian_batched():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 4).astype(np.float32)
    H = Hessian(lambda t: paddle.sum(t * t, axis=-1, keepdim=True),
                paddle.to_tensor(x), is_batched=True)
    assert H.shape == (3, 4, 4)
    full = np.asarray(H[:])
    for b in range(3):
        np.testing.assert_allclose(full[b], 2 * np.eye(4), rtol=1e-5, atol=1e-6)


def test_legacy_jacobian_single():
    x = paddle.ones([2, 2], dtype="float32")
    j = jacobian(lambda t: paddle.matmul(t, t), x)
    expected = np.array([[2., 1., 1., 0.],
                         [1., 2., 0., 1.],
                         [1., 0., 2., 1.],
                         [0., 1., 1., 2.]], np.float32)
    np.testing.assert_allclose(j.numpy(), expected, rtol=1e-5)


def test_legacy_jacobian_multi_input():
    x = paddle.ones([2, 2], dtype="float32")
    y = paddle.ones([2, 2], dtype="float32") * 2
    j = jacobian(lambda a, b: paddle.matmul(a, b), [x, y])
    assert isinstance(j, tuple) and len(j) == 2
    assert j[0].shape == [4, 4] and j[1].shape == [4, 4]
    gx = jax.jacrev(lambda a, b: (a @ b).reshape(-1), argnums=(0, 1))(x.numpy(), y.numpy())
    np.testing.assert_allclose(j[0].numpy(), np.asarray(gx[0]).reshape(4, 4), rtol=1e-5)
    np.testing.assert_allclose(j[1].numpy(), np.asarray(gx[1]).reshape(4, 4), rtol=1e-5)


def test_legacy_batch_jacobian_reference_example():
    # reference functional.py:842 docstring example
    x = paddle.ones([4, 2], dtype="float64")
    weight = paddle.ones([2, 4], dtype="float64")
    y = paddle.ones([4, 2], dtype="float64")

    def func(t):
        return paddle.matmul(paddle.matmul(t, weight), y)

    bj = batch_jacobian(func, x)
    assert bj.shape == [2, 8]
    np.testing.assert_allclose(bj.numpy(), np.full((2, 8), 4.0), rtol=1e-6)


def test_legacy_hessian():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 2).astype(np.float32)

    def func(t):
        return paddle.sum(paddle.matmul(t, t))

    h = hessian(func, paddle.to_tensor(x))
    golden = jax.hessian(lambda a: (a @ a).sum())(x).reshape(4, 4)
    np.testing.assert_allclose(h.numpy(), np.asarray(golden), rtol=1e-4, atol=1e-5)


def test_legacy_batch_hessian():
    x = paddle.ones([4, 2], dtype="float64")

    def func(t):
        return paddle.sum(t * t, axis=-1, keepdim=True)

    bh = batch_hessian(func, x)
    assert bh.shape == [2, 8]
    # golden: per-sample hessian of sum(x^2) is 2I; layout [ni, B*nj]
    golden = np.zeros((2, 8))
    for b in range(4):
        golden[:, b * 2:(b + 1) * 2] = 2 * np.eye(2)
    np.testing.assert_allclose(bh.numpy(), golden, rtol=1e-6)


def test_vhp():
    rng = np.random.RandomState(4)
    x = rng.randn(5).astype(np.float32)
    v = rng.randn(5).astype(np.float32)

    def func(t):
        return paddle.sum(paddle.exp(t) + t * t)

    out, hv = vhp(func, paddle.to_tensor(x), v=paddle.to_tensor(v))
    f = lambda a: (jnp.exp(a) + a * a).sum()
    golden_out = f(x)
    golden_hv = np.asarray(jax.hessian(f)(x)) @ v
    np.testing.assert_allclose(float(out), float(golden_out), rtol=1e-5)
    np.testing.assert_allclose(hv.numpy(), golden_hv, rtol=1e-4, atol=1e-5)


def test_jacobian_mlp_params():
    """VERDICT #10 done-criterion: parity vs jax.jacrev on MLP params."""
    rng = np.random.RandomState(5)
    w1 = rng.randn(4, 8).astype(np.float32) * 0.3
    w2 = rng.randn(8, 3).astype(np.float32) * 0.3
    xin = rng.randn(2, 4).astype(np.float32)

    def mlp(a, b):
        h = paddle.tanh(paddle.matmul(paddle.to_tensor(xin), a))
        return paddle.matmul(h, b)

    J = Jacobian(mlp, [paddle.to_tensor(w1), paddle.to_tensor(w2)])
    assert J.shape == (6, 32 + 24)

    def flat_mlp(f):
        a = f[:32].reshape(4, 8)
        b = f[32:].reshape(8, 3)
        return (jnp.tanh(xin @ a) @ b).reshape(-1)

    flat0 = np.concatenate([w1.reshape(-1), w2.reshape(-1)])
    golden = jax.jacrev(flat_mlp)(flat0)
    np.testing.assert_allclose(np.asarray(J[:]), np.asarray(golden), rtol=1e-4, atol=1e-5)
