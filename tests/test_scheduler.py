"""Direct unit coverage of `RaggedScheduler.plan` (and the tenancy
scheduler's class-aware composition) — until now the scheduler was
only exercised through engine integration tests. A duck-typed fake
decoder keeps these pure-host and fast: the scheduler only reads
`step_hbm_bytes()`, `cfg.num_params()` and `max_batch`."""
import math

import pytest

from paddle_tpu.cost_model import ragged_chunk_tokens
from paddle_tpu.serving import (SLO_LATENCY, SLO_THROUGHPUT,
                                RaggedScheduler, TenantScheduler)


class _FakeCfg:
    def __init__(self, params=2_000_000):
        self._p = params

    def num_params(self):
        return self._p


class _FakeDec:
    def __init__(self, max_batch=4, hbm=1 << 20, params=2_000_000):
        self.max_batch = max_batch
        self.cfg = _FakeCfg(params)
        self._hbm = hbm

    def step_hbm_bytes(self):
        return self._hbm


def _sched(max_batch=4, chunk_tokens=8, k_max=8, cls=RaggedScheduler):
    return cls(_FakeDec(max_batch=max_batch), chunk_tokens=chunk_tokens,
               k_max=k_max, host_sync_s=4e-4)


# ----------------------------------------------------- construction


def test_chunk_budget_normalizes_down_to_pow2_with_floor_one():
    """The per-slot chunk budget is normalized DOWN to a power of two
    (plan buckets widths pow2 — rounding UP would exceed the priced
    per-tick budget), floored at 1."""
    assert _sched(chunk_tokens=8).chunk_tokens == 8
    assert _sched(chunk_tokens=13).chunk_tokens == 8
    assert _sched(chunk_tokens=3).chunk_tokens == 2
    assert _sched(chunk_tokens=1).chunk_tokens == 1
    assert _sched(chunk_tokens=0).chunk_tokens == 1


def test_priced_chunk_budget_respects_floor_and_cap():
    """`cost_model.ragged_chunk_tokens`: a compute-tight model clamps
    at the floor (progress on prompts is guaranteed), an HBM-dominated
    one at the cap (per-tick latency jitter stays bounded)."""
    # enormous per-token FLOPs: nothing hides under the HBM leg
    assert ragged_chunk_tokens(1 << 20, 1e15) == 8
    # free compute: the cap bounds the tick's token budget
    assert ragged_chunk_tokens(1 << 30, 1.0) == 256
    assert ragged_chunk_tokens(1 << 30, 0.0) == 256


# ------------------------------------------------------------- plan


def test_plan_empty_live_returns_none():
    s = _sched()
    assert s.plan({}, {}, [0] * 4) is None


def test_plan_all_frozen_returns_none():
    """Every emittable tick already in flight (or budget exhausted):
    no horizon can make progress."""
    s = _sched()
    live = {0: 100, 1: 101}
    # budget fully in flight on slot 0, exhausted on slot 1
    assert s.plan(live, {0: 4, 1: 0}, {0: 4, 1: 0}) is None


def test_plan_pure_decode_full_horizon():
    s = _sched(k_max=8)
    live = {0: 100, 1: 101}
    plan = s.plan(live, {0: 16, 1: 16}, [0] * 4)
    assert (plan.k, plan.w) == (8, 1)
    assert plan.n_chunks == 0 and plan.prefill_rows == 0
    assert plan.emit_ticks == {0: 8, 1: 8}
    # packed bucket: pow2, floored at the slot count
    assert plan.t_tokens == 4


def test_plan_consumes_suffix_and_caps_emit_ticks_by_budget():
    """A prefilling slot's chunk ticks don't emit; emit_ticks is
    capped at budget - inflight so the device/host in-flight invariant
    holds exactly."""
    s = _sched(chunk_tokens=8, k_max=8)
    s.admit(0, 20)                       # ceil(20/8) = 3 chunk ticks
    live = {0: 100, 1: 101}
    assert s.prefilling(0) and s.suffix_left(0) == 20
    assert s.stall_ticks(0) == 2
    plan = s.plan(live, {0: 16, 1: 4}, [0] * 4)
    assert plan.w == 8
    # k clamped to the chunk ticks the stream needs (pow2 below 3)
    assert plan.k == 2
    assert plan.prefill_rows == 1 and plan.n_chunks == 2
    # slot 0: both ticks consume chunks, none emits; slot 1 emits both
    assert plan.emit_ticks == {0: 0, 1: 2}
    assert s.suffix_left(0) == 20 - 2 * 8
    # slot 1 now fully in flight: its ticks are filler (emit 0), but
    # slot 0's remaining chunk work still makes a horizon
    plan2 = s.plan(live, {0: 16, 1: 4}, {0: 0, 1: 4})
    assert plan2.emit_ticks[1] == 0
    assert s.suffix_left(0) == 0


def test_plan_width_covers_shortest_suffix_not_cap():
    """A 5-token prompt must not inflate the whole batch to the cap:
    w is the min-cover pow2 of the longest PENDING suffix."""
    s = _sched(chunk_tokens=64, k_max=8)
    s.admit(0, 5)
    plan = s.plan({0: 100}, {0: 8}, [0] * 4)
    assert plan.w == 8                   # pow2 >= 5, way below cap 64
    assert plan.k == 1


def test_plan_t_tokens_is_pow2_total_token_bucket():
    s = _sched(chunk_tokens=8, k_max=8, max_batch=4)
    s.admit(0, 16)
    live = {0: 100, 1: 101, 2: 102}
    plan = s.plan(live, {0: 8, 1: 8, 2: 8}, [0] * 4)
    # tick 0 total: slot 0 pays min(16, 8)=8, slots 1-2 pay 1 each ->
    # 10 -> pow2 16 (already >= the slot-count floor of 4)
    assert plan.t_tokens == 16


# --------------------------------------------------- TenantScheduler


def test_latency_row_preempts_chunk_budget_vs_throughput_backlog():
    """Single latency row vs a full throughput backlog: w sizes to the
    LATENCY suffix (the longer throughput suffix no longer stretches
    the drain) and k clamps to the ticks the latency stream needs."""
    s = _sched(chunk_tokens=16, k_max=8, cls=TenantScheduler)
    s.admit(0, 12)
    s.set_slo(0, SLO_LATENCY)
    s.admit(1, 120)                      # long throughput prompt
    s.set_slo(1, SLO_THROUGHPUT)
    live = {0: 100, 1: 101}
    plan = s.plan(live, {0: 8, 1: 8}, [0] * 4)
    assert plan.w == 16                  # min-cover of the 12-token
    assert plan.k == 1                   # latency suffix, one tick
    # the throughput row BACKFILLED the same tick with its own chunk
    assert plan.prefill_rows == 2
    assert s.suffix_left(0) == 0 and s.suffix_left(1) == 120 - 16


def test_throughput_only_composition_falls_back_to_base():
    base = _sched(chunk_tokens=8, k_max=8)
    ten = _sched(chunk_tokens=8, k_max=8, cls=TenantScheduler)
    for s in (base, ten):
        s.admit(0, 20)
    ten.set_slo(0, SLO_THROUGHPUT)
    assert ten._compose({0: 100}) == base._compose({0: 100})


def test_latency_queue_pressure_clamps_horizon():
    """A latency request WAITING in the queue caps pure-decode
    horizons at the roofline-derived latency K, so the next admission
    boundary arrives within the class target."""
    s = _sched(chunk_tokens=8, k_max=32, cls=TenantScheduler)
    assert 1 <= s.k_latency <= s.k_max
    live = {0: 100}
    s.set_slo(0, SLO_THROUGHPUT)
    w, k_limit = s._compose(live)
    assert (w, k_limit) == (1, 32)
    s.note_queue(True)
    w, k_limit = s._compose(live)
    assert k_limit == min(32, s.k_latency)
    s.note_queue(False)
    assert s._compose(live)[1] == 32


def test_slo_targets_are_roofline_priced():
    """Per-class p99 targets come from cost_model.slo_p99_target_s —
    the latency class syncs more often, so its per-boundary target is
    at or below the throughput class's."""
    s = _sched(cls=TenantScheduler)
    t = s.slo_targets_s
    assert 0 < t[SLO_LATENCY] <= t[SLO_THROUGHPUT]


def test_retire_clears_slo_and_suffix():
    s = _sched(cls=TenantScheduler)
    s.admit(2, 9)
    s.set_slo(2, SLO_LATENCY)
    s.retire(2)
    assert not s.prefilling(2)
    assert 2 not in s._slo
