"""Every example stays syntactically valid and importable-name-clean
(the cheap rot check; heavier example flows run in their own benches)."""
import ast
import glob
import os

import pytest

EXAMPLES = sorted(glob.glob(os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "*.py")))


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p)
                                                for p in EXAMPLES])
def test_example_parses(path):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    # every example must be runnable as a script
    assert any(isinstance(n, ast.If) and ast.unparse(n.test).startswith(
        "__name__") for n in tree.body), f"{path}: no __main__ guard"
