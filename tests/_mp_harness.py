"""Shared plumbing for tests that spawn real OS processes.

Three families of tests launch fresh python processes that must come up
on the CPU backend and (for the distributed ones) meet a coordinator
barrier on wall-clock deadlines: the launch smoke tests, the elastic
kill/resume integration tests, and the fleet-serving cross-process
shared-tier tests. They all need the same three pieces, previously
copy-pasted per file:

- `mp_env()` — a child environment that strips the parent's
  accelerator/XLA state (a child inheriting `XLA_FLAGS` /
  `JAX_PLATFORMS` from a pytest process that already initialized a
  backend comes up wrong), prepends the repo to `PYTHONPATH`, and
  widens `PADDLE_TPU_DIST_INIT_TIMEOUT` to 180 s — the
  coordinator-barrier fail-fast default (60 s) is sized for the
  RESTART loop where the peer is known alive; first boots late in a
  loaded tier-1 sweep legitimately exceed it (the PR-12 load flake).
  `cpu_devices=N` additionally routes through the launcher's
  `force_cpu_devices` (PJRT discovery-var strip + gloo collectives +
  `--xla_force_host_platform_device_count`).
- `retry_under_load` — load-flake containment for deadline tests:
  one clean retry in a fresh subdir, or a skip when the 1-minute load
  average says the box is saturated (a deadline test on a saturated
  box measures the box, not the code under test). A real bug still
  fails: it reproduces on the quiet retry.
- `run_worker()` — run one child script to completion and fail with
  its full output on a nonzero exit (subprocess stderr is otherwise
  swallowed into an opaque CalledProcessError).
"""
import functools
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.launch import force_cpu_devices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mp_env(extra=None, cpu_devices=None):
    """Child-process environment for spawning fresh python workers:
    parent accelerator/XLA state stripped, repo importable, the
    distributed init fail-fast widened for loaded boxes. `cpu_devices`
    forces N virtual CPU devices (collectives-capable via gloo)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PADDLE_TPU_DIST_INIT_TIMEOUT"] = "180"
    if cpu_devices:
        force_cpu_devices(env, cpu_devices)
    if extra:
        env.update(extra)
    return env


def retry_under_load(test):
    """Load-flake containment for wall-clock-deadline process tests
    (the PR-12 flake, still seen rarely after the 180 s init-timeout
    widening): each spawns python workers that must import jax (and
    possibly meet a coordinator barrier) on deadlines no timeout can
    make robust on a box ALSO running the rest of the tier-1 sweep's
    GC cliff. Policy: one clean retry in a fresh subdir; if the
    1-minute load average says the box is saturated (beyond ~1.5x its
    cores), skip instead. A real bug still fails: it reproduces on
    the quiet retry.

    The bar is 1.5x cores with NO absolute floor: the old
    `max(2.0, ...)` floor let a 1-core box retry at load 2.0 (200%
    saturated) and fail the retry too. Load is sampled THREE times —
    at the first failure, again right before the retry (the 1-minute
    average lags the GC cliff that caused the failure; a retry
    launched into the same spike measures the spike), and once more
    AROUND a failing retry: a box that saturated mid-retry (one F in
    PR 17's measured sweep, clean in isolation) gets a skip, not a
    fail — only a retry that fails on a quiet box is a real bug."""
    @functools.wraps(test)
    def wrapper(tmp_path):
        bar = 1.5 * (os.cpu_count() or 1)

        def saturated():
            return _loadavg() > bar

        def skip(when, e):
            pytest.skip(f"box saturated {when} (load "
                        f"{_loadavg():.1f} on "
                        f"{os.cpu_count()} cores) — deadline "
                        f"test skipped after: {e!r:.200}")

        try:
            return test(tmp_path)
        except Exception as e:
            if saturated():
                skip("", e)
            # give the lagging average a beat to see the spike that
            # just failed us, then re-check before burning the retry
            time.sleep(5.0)
            if saturated():
                skip("before retry", e)
            retry_dir = tmp_path / "retry"
            retry_dir.mkdir(exist_ok=True)
            try:
                return test(retry_dir)
            except Exception as e2:
                # the quiet-at-launch box may have saturated DURING
                # the retry (mid-sweep GC cliff): re-sample before
                # ruling the failure real
                if saturated():
                    skip("during retry", e2)
                raise
    return wrapper


def _loadavg():
    """1-minute load average — module-level so the fake-load unit test
    can monkeypatch it; everything in `retry_under_load` reads load
    through here."""
    return os.getloadavg()[0]


def run_worker(script, args=(), env=None, timeout=300):
    """Run one child python script to completion; fail LOUD (full
    stdout+stderr in the assertion) on nonzero exit. Returns the
    CompletedProcess for output assertions."""
    proc = subprocess.run(
        [sys.executable, str(script), *[str(a) for a in args]],
        env=env if env is not None else mp_env(),
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"worker {script} exited {proc.returncode}\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    return proc
