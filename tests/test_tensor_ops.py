"""Per-op unit tests vs numpy golden — mirrors the reference test strategy
(python/paddle/fluid/tests/unittests/test_*_op.py, numpy-checked)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return t.numpy()


class TestCreation:
    def test_zeros_ones_full(self):
        assert _np(paddle.zeros([2, 3])).tolist() == np.zeros([2, 3]).tolist()
        assert _np(paddle.ones([2])).tolist() == [1, 1]
        assert _np(paddle.full([2, 2], 7, "int32")).tolist() == [[7, 7], [7, 7]]

    def test_arange_linspace_eye(self):
        assert _np(paddle.arange(5)).tolist() == [0, 1, 2, 3, 4]
        assert _np(paddle.arange(1, 10, 3)).tolist() == [1, 4, 7]
        np.testing.assert_allclose(_np(paddle.linspace(0, 1, 5)), np.linspace(0, 1, 5), rtol=1e-6)
        np.testing.assert_array_equal(_np(paddle.eye(3)), np.eye(3))

    def test_to_tensor_dtype(self):
        t = paddle.to_tensor([1.0, 2.0])
        assert t.dtype == paddle.float32
        t64 = paddle.to_tensor([1, 2])
        assert "int" in t64.dtype.name

    def test_tril_triu_diag(self):
        x = paddle.to_tensor(np.arange(9).reshape(3, 3).astype("float32"))
        np.testing.assert_array_equal(_np(paddle.tril(x)), np.tril(_np(x)))
        np.testing.assert_array_equal(_np(paddle.triu(x, 1)), np.triu(_np(x), 1))
        np.testing.assert_array_equal(_np(paddle.diag(paddle.to_tensor([1.0, 2.0]))),
                                      np.diag([1.0, 2.0]))


class TestMath:
    def setup_method(self, _):
        paddle.seed(42)
        self.x = paddle.rand([4, 5])
        self.y = paddle.rand([4, 5])

    def test_elementwise(self):
        a, b = _np(self.x), _np(self.y)
        np.testing.assert_allclose(_np(self.x + self.y), a + b, rtol=1e-6)
        np.testing.assert_allclose(_np(self.x * self.y), a * b, rtol=1e-6)
        np.testing.assert_allclose(_np(self.x / (self.y + 1)), a / (b + 1), rtol=1e-6)
        np.testing.assert_allclose(_np(paddle.exp(self.x)), np.exp(a), rtol=1e-6)
        np.testing.assert_allclose(_np(paddle.log(self.x + 1)), np.log(a + 1), rtol=1e-6)
        np.testing.assert_allclose(_np(paddle.rsqrt(self.x + 1)), 1 / np.sqrt(a + 1), rtol=1e-5)

    def test_scalar_ops_keep_dtype(self):
        z = self.x * 2 + 1
        assert z.dtype == paddle.float32
        np.testing.assert_allclose(_np(z), _np(self.x) * 2 + 1, rtol=1e-6)

    def test_reductions(self):
        a = _np(self.x)
        np.testing.assert_allclose(_np(paddle.sum(self.x)), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.mean(self.x, axis=1)), a.mean(1), rtol=1e-6)
        np.testing.assert_allclose(_np(paddle.max(self.x, axis=0)), a.max(0), rtol=1e-6)
        np.testing.assert_allclose(_np(paddle.prod(self.x, axis=1)), a.prod(1), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.logsumexp(self.x)),
                                   np.log(np.exp(a).sum()), rtol=1e-5)

    def test_matmul(self):
        m = paddle.rand([3, 4])
        n = paddle.rand([4, 5])
        np.testing.assert_allclose(_np(paddle.matmul(m, n)), _np(m) @ _np(n), rtol=1e-5)
        np.testing.assert_allclose(_np(m @ n), _np(m) @ _np(n), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.matmul(m, n.T if False else paddle.to_tensor(_np(n).T), transpose_y=True)),
            _np(m) @ _np(n), rtol=1e-5)

    def test_cumsum_clip(self):
        a = _np(self.x)
        np.testing.assert_allclose(_np(paddle.cumsum(self.x, axis=1)), a.cumsum(1), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.clip(self.x, 0.2, 0.8)), a.clip(0.2, 0.8), rtol=1e-6)

    def test_inplace(self):
        t = paddle.to_tensor([1.0, 4.0, 9.0])
        t.sqrt_()
        np.testing.assert_allclose(_np(t), [1, 2, 3], rtol=1e-6)
        t.add_(paddle.to_tensor([1.0, 1.0, 1.0]))
        np.testing.assert_allclose(_np(t), [2, 3, 4], rtol=1e-6)


class TestManipulation:
    def test_reshape_transpose_concat(self):
        x = paddle.arange(12, dtype="float32")
        r = x.reshape([3, 4])
        assert r.shape == [3, 4]
        t = r.transpose([1, 0])
        assert t.shape == [4, 3]
        c = paddle.concat([r, r], axis=0)
        assert c.shape == [6, 4]
        s = paddle.stack([r, r], axis=0)
        assert s.shape == [2, 3, 4]

    def test_split_chunk(self):
        x = paddle.arange(12, dtype="float32").reshape([3, 4])
        p = paddle.split(x, [1, 3], axis=1)
        assert p[0].shape == [3, 1] and p[1].shape == [3, 3]
        p = paddle.split(x, [1, -1], axis=1)
        assert p[1].shape == [3, 3]

    def test_gather_scatter(self):
        x = paddle.to_tensor(np.arange(12).reshape(4, 3).astype("float32"))
        idx = paddle.to_tensor([0, 2])
        g = paddle.gather(x, idx, axis=0)
        np.testing.assert_array_equal(_np(g), _np(x)[[0, 2]])
        upd = paddle.ones([2, 3])
        s = paddle.scatter(x, idx, upd)
        expect = _np(x).copy()
        expect[[0, 2]] = 1
        np.testing.assert_array_equal(_np(s), expect)

    def test_where_masked(self):
        x = paddle.to_tensor([1.0, -2.0, 3.0])
        out = paddle.where(x > 0, x, paddle.zeros_like(x))
        np.testing.assert_array_equal(_np(out), [1, 0, 3])

    def test_squeeze_tile_flip(self):
        x = paddle.ones([1, 3, 1])
        assert paddle.squeeze(x).shape == [3]
        assert paddle.unsqueeze(paddle.ones([3]), [0, 2]).shape == [1, 3, 1]
        np.testing.assert_array_equal(_np(paddle.flip(paddle.arange(3), 0)), [2, 1, 0])
        assert paddle.tile(paddle.ones([2]), [3]).shape == [6]

    def test_getitem_setitem(self):
        x = paddle.arange(12, dtype="float32").reshape([3, 4])
        assert x[1, 2].item() == 6.0
        assert x[:, 1].shape == [3]
        x[0, 0] = 100.0
        assert x[0, 0].item() == 100.0


class TestLogicSearch:
    def test_compare(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        y = paddle.to_tensor([2.0, 2.0, 2.0])
        np.testing.assert_array_equal(_np(x < y), [True, False, False])
        np.testing.assert_array_equal(_np(paddle.equal(x, y)), [False, True, False])
        assert paddle.allclose(x, x).item()

    def test_topk_argsort(self):
        x = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
        v, i = paddle.topk(x, 2)
        np.testing.assert_array_equal(_np(v), [5, 4])
        np.testing.assert_array_equal(_np(i), [4, 2])
        np.testing.assert_array_equal(_np(paddle.argsort(x)), np.argsort(_np(x), kind="stable"))

    def test_argmax_unique(self):
        x = paddle.to_tensor([[1.0, 5.0], [7.0, 2.0]])
        assert paddle.argmax(x).item() == 2
        np.testing.assert_array_equal(_np(paddle.argmax(x, axis=1)), [1, 0])
        u = paddle.unique(paddle.to_tensor([3, 1, 2, 1, 3]))
        np.testing.assert_array_equal(_np(u), [1, 2, 3])


class TestLinalgStat:
    def test_norm_det_inv(self):
        a = np.array([[2.0, 0.0], [1.0, 3.0]], np.float32)
        x = paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.linalg.det(x).item(), np.linalg.det(a), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.linalg.inv(x)), np.linalg.inv(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.norm(x).item(), np.linalg.norm(a), rtol=1e-5)

    def test_svd_qr_eigh(self):
        paddle.seed(1)
        x = paddle.rand([4, 3])
        u, s, vt = paddle.linalg.svd(x)
        rec = _np(u) @ np.diag(_np(s)) @ _np(vt)
        np.testing.assert_allclose(rec, _np(x), atol=1e-5)
        q, r = paddle.linalg.qr(x)
        np.testing.assert_allclose(_np(q) @ _np(r), _np(x), atol=1e-5)

    def test_stat(self):
        paddle.seed(2)
        x = paddle.rand([10, 5])
        a = _np(x)
        np.testing.assert_allclose(_np(paddle.std(x)), a.std(ddof=1), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.var(x, axis=0)), a.var(0, ddof=1), rtol=1e-4)
        np.testing.assert_allclose(_np(paddle.median(x)), np.median(a), rtol=1e-5)

    def test_einsum(self):
        a = paddle.rand([2, 3])
        b = paddle.rand([3, 4])
        np.testing.assert_allclose(_np(paddle.einsum("ij,jk->ik", a, b)),
                                   _np(a) @ _np(b), rtol=1e-5)


class TestRandom:
    def test_determinism(self):
        paddle.seed(123)
        a = paddle.randn([8])
        paddle.seed(123)
        b = paddle.randn([8])
        np.testing.assert_array_equal(_np(a), _np(b))

    def test_shapes_ranges(self):
        r = paddle.randint(0, 10, [100])
        assert _np(r).min() >= 0 and _np(r).max() < 10
        u = paddle.uniform([50], min=2.0, max=3.0)
        assert _np(u).min() >= 2.0 and _np(u).max() <= 3.0
        p = paddle.randperm(10)
        np.testing.assert_array_equal(np.sort(_np(p)), np.arange(10))


def test_set_value_in_place():
    """Reference varbase set_value: same-shape in-place assignment, cast
    to the tensor's dtype; shape mismatch raises; Layer-held Parameters
    observe the change (weight-surgery pattern)."""
    t = paddle.ones([2, 3])
    t.set_value(np.full((2, 3), 7.0))
    np.testing.assert_allclose(t.numpy(), 7.0)
    t.set_value(paddle.zeros([2, 3]))
    np.testing.assert_allclose(t.numpy(), 0.0)
    with pytest.raises(ValueError, match="shape mismatch"):
        t.set_value(np.zeros((3, 2), "float32"))

    lin = paddle.nn.Linear(3, 2)
    w = np.arange(6, dtype="float32").reshape(3, 2)
    lin.weight.set_value(w)
    out = lin(paddle.to_tensor(np.ones((1, 3), "float32")))
    np.testing.assert_allclose(out.numpy(), w.sum(0)[None] + lin.bias.numpy(),
                               rtol=1e-6)


def test_gradient_returns_numpy_or_none():
    """Reference varbase gradient(): numpy of .grad, None before any
    backward (their own docs' x**4 example)."""
    x = paddle.to_tensor(5.0, stop_gradient=False)
    (x ** 4.0).backward()
    np.testing.assert_allclose(x.gradient(), 500.0)
    assert paddle.ones([2]).gradient() is None


def test_to_sparse_coo_round_trips():
    d = paddle.to_tensor(np.array([[0.0, 1.0, 0.0], [2.0, 0.0, 3.0]],
                                  "float32"))
    sp = d.to_sparse_coo(2)
    assert sp.nnz() == 3
    np.testing.assert_allclose(sp.to_dense().numpy(), d.numpy())
    # hybrid: leading dim sparse, values are row slices
    sp1 = d.to_sparse_coo(1)
    assert sp1.nnz() == 2
    np.testing.assert_allclose(sp1.to_dense().numpy(), d.numpy())
    with pytest.raises(ValueError, match="sparse_dim"):
        d.to_sparse_coo(3)
    assert d.to_dense() is d


def test_last_tensor_method_func_names_attached():
    """The reference patches 220 functions onto Tensor
    (tensor/__init__.py tensor_method_func); these four were the last
    missing as METHODS (the free functions already existed)."""
    t = paddle.ones([2, 3])
    assert int(t.rank()) == 2
    assert not bool(t.is_empty())
    assert bool(paddle.zeros([0, 3]).is_empty())
    assert t.is_tensor()
    assert t.broadcast_shape([4, 1, 3]) == [4, 2, 3]
