"""The lint-memory CI gate: every BASELINE config's static per-device
HBM estimate must match its COMMITTED memory manifest
(memory_manifests/<config>.json, regenerated with
`python -m paddle_tpu.analysis --write-manifests`), the estimator must
agree with XLA's own `compiled.memory_analysis()` on CPU within 20%,
and an injected peak-HBM regression must fail the gate.

Runs inside the standard tier-1 sweep; select alone with
`-m lint_memory`. Lowerings ride the per-process cache in
paddle_tpu.analysis.baseline; compiles ride the persistent XLA cache.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis import (AnalysisContext, PassManager,
                                 estimate_jaxpr_memory,
                                 load_memory_manifest, manifest_drift)
from paddle_tpu.analysis.baseline import (BASELINE_CONFIGS,
                                          PROGRAM_CONFIGS,
                                          lowered_program)
from paddle_tpu.analysis.lowering import ArgInfo

pytestmark = pytest.mark.lint_memory


@pytest.fixture(scope="module")
def pass_manager():
    return PassManager()


def _fresh_report(name, pm, with_manifest=True):
    program, ctx, fwd = lowered_program(name)
    if with_manifest:
        ctx.memory_manifest = load_memory_manifest(name)
    return program, ctx, pm.run(program, ctx)


@pytest.mark.parametrize(
    "name", sorted(BASELINE_CONFIGS) + sorted(PROGRAM_CONFIGS))
def test_memory_manifest_is_committed_and_current(name, pass_manager):
    """Gate: a fresh estimate agrees with the committed manifest (no
    MEM-PEAK-REGRESSION / SHARD-WIRE-REGRESSION, no raw drift) — for
    the five BASELINE forwards AND the PROGRAM captures (gpt_decode:
    the fused multi-step serving loop)."""
    from paddle_tpu.analysis import build_memory_manifest
    program, ctx, report = _fresh_report(name, pass_manager)
    assert ctx.memory_manifest is not None, (
        f"memory_manifests/{name}.json is not committed — run "
        "python -m paddle_tpu.analysis --write-manifests")
    for rule in ("MEM-PEAK-REGRESSION", "MEM-OVER-BUDGET",
                 "SHARD-WIRE-REGRESSION"):
        assert report.by_rule(rule) == [], \
            "\n".join(str(f) for f in report.by_rule(rule))
    drift = manifest_drift(build_memory_manifest(name, report),
                           ctx.memory_manifest)
    assert drift == [], "\n".join(drift)
    mem = report.metrics["memory"]
    assert mem["available"] and mem["peak_bytes"] > 0
    assert mem["peak_bytes"] >= mem["args_bytes"]
    # attribution names real buffers, biggest first
    top = mem["top_live"]
    assert top and top[0]["device_bytes"] >= top[-1]["device_bytes"]


@pytest.mark.parametrize("name", sorted(BASELINE_CONFIGS))
def test_static_peak_within_20pct_of_xla(name):
    """The acceptance cross-check: the CPU-calibrated liveness estimate
    lands within 20% of XLA's own buffer-assignment numbers where this
    jaxlib exposes them on CPU."""
    from paddle_tpu.analysis.baseline import build_config
    model, examples, _ = build_config(name)
    import jax.tree_util as jtu
    from paddle_tpu.framework.core import Tensor
    from paddle_tpu.nn.layer_base import (buffer_pytree, functional_call,
                                          state_pytree)
    params = state_pytree(model)
    params.update(buffer_pytree(model))

    def pure(p, *args):
        with functional_call(model, p):
            out = model(*[Tensor(a) for a in args])
        return jtu.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    traced = jax.jit(pure).trace(params, *examples)
    ma = traced.lower().compile().memory_analysis()
    if ma is None or ma.argument_size_in_bytes == 0:
        pytest.skip("compiled.memory_analysis() unavailable on CPU here")
    xla_peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    est = estimate_jaxpr_memory(traced.jaxpr, cpu_calibrated=True)
    assert abs(est.peak_bytes - xla_peak) <= 0.20 * xla_peak, (
        name, est.peak_bytes, xla_peak, est.peak_bytes / xla_peak)


@pytest.mark.parametrize("name", ["gpt"])
def test_gate_fails_on_injected_peak_regression(name, pass_manager):
    """A +30% per-device peak regression against the committed manifest
    must produce an ERROR (the gate's reason to exist)."""
    from paddle_tpu.analysis import Severity
    program, ctx, fwd = lowered_program(name)
    fresh = pass_manager.run(program, ctx).metrics["memory"]["peak_bytes"]
    # simulate: the committed baseline was 30% smaller than this run
    ctx.memory_manifest = {
        "per_device_peak_bytes": int(fresh / 1.3),
        "collectives": {"total_wire_bytes": 0},
    }
    report = pass_manager.run(program, ctx)
    hits = report.by_rule("MEM-PEAK-REGRESSION")
    assert hits and hits[0].severity == Severity.ERROR, \
        [str(f) for f in report.findings]
    assert report.errors


def test_manifest_drift_detects_tampering():
    committed = load_memory_manifest("gpt")
    assert committed is not None
    tampered = dict(committed, per_device_peak_bytes=1)
    assert manifest_drift(committed, committed) == []
    drift = manifest_drift(committed, tampered)
    assert drift and "per_device_peak_bytes" in drift[0]
    assert manifest_drift(committed, None)  # missing file is drift


def test_cli_check_mode_clean_and_memory_output(capsys):
    """`--check` exits 0 on the committed state; `--memory` prints the
    HBM breakdown."""
    from paddle_tpu.analysis.__main__ import main
    assert main(["gpt", "--check"]) == 0
    out = capsys.readouterr().out
    assert "manifests current" in out
    assert main(["gpt", "--memory"]) == 0
    out = capsys.readouterr().out
    assert "per-device peak" in out and "sharding:" in out


# ------------------------------------------------- estimator unit proofs


def test_donated_args_free_at_last_use():
    """Donation credit: a donated arg dies after its last use, so the
    peak drops vs the caller-owned version of the same program."""
    big = jnp.zeros((256, 256), jnp.float32)

    def f(a, b):
        c = a + 1.0          # a dead afterwards
        return c * b

    traced = jax.jit(f).trace(big, big)
    base = [ArgInfo(name="a", role="param", shape=(256, 256),
                    dtype="float32", bytes=big.nbytes),
            ArgInfo(name="b", role="param", shape=(256, 256),
                    dtype="float32", bytes=big.nbytes)]
    keep = estimate_jaxpr_memory(traced.jaxpr, arg_infos=base)
    donated = [ArgInfo(**{**vars(i), "donated": True}) for i in base]
    freed = estimate_jaxpr_memory(traced.jaxpr, arg_infos=donated)
    assert freed.peak_bytes < keep.peak_bytes
    assert freed.donated_bytes == 2 * big.nbytes


def test_per_device_division_by_shard_count():
    """An 8-way-sharded arg costs 1/8 per device; replicated costs full."""
    x = jnp.zeros((64, 64), jnp.float32)

    def f(a):
        return a * 2.0

    traced = jax.jit(f).trace(x)
    rep = estimate_jaxpr_memory(traced.jaxpr, arg_infos=[
        ArgInfo(name="x", role="batch", shape=(64, 64), dtype="float32",
                bytes=x.nbytes, shard_count=1)])
    shard = estimate_jaxpr_memory(traced.jaxpr, arg_infos=[
        ArgInfo(name="x", role="batch", shape=(64, 64), dtype="float32",
                bytes=x.nbytes, shard_count=8)])
    assert rep.args_bytes == x.nbytes
    assert shard.args_bytes == x.nbytes // 8
    # the intermediate inherits the operand's sharding (propagation)
    assert shard.peak_bytes <= rep.peak_bytes // 4


def test_per_host_accounting_dp_over_hosts():
    """dp-over-hosts distinct-bytes-per-host: an 8-way dp-sharded
    batch on a 4-host mesh costs 1/4 per host (each host holds 2
    distinct shards), while the replicated param costs its FULL size
    on every host — per-device division would claim 1/8 and 1/1."""
    xb = jnp.zeros((64, 64), jnp.float32)
    wp = jnp.zeros((64, 64), jnp.float32)

    def f(a, w):
        return a @ w

    traced = jax.jit(f).trace(xb, wp)
    infos = [ArgInfo(name="batch", role="batch", shape=(64, 64),
                     dtype="float32", bytes=xb.nbytes, shard_count=8),
             ArgInfo(name="w", role="param", shape=(64, 64),
                     dtype="float32", bytes=wp.nbytes, shard_count=1)]
    est = estimate_jaxpr_memory(traced.jaxpr, arg_infos=infos, n_hosts=4)
    assert est.n_hosts == 4
    assert est.host_args_bytes == xb.nbytes // 4 + wp.nbytes
    # per-host distinct bytes sit between per-device and global
    assert est.host_peak_bytes >= est.peak_bytes
    assert "per_host" in est.to_dict()
    assert est.to_dict()["per_host"]["n_hosts"] == 4
    # single-host estimates stay byte-stable: no per_host block at all
    single = estimate_jaxpr_memory(traced.jaxpr, arg_infos=infos)
    assert single.n_hosts == 1 and "per_host" not in single.to_dict()


def test_per_host_accounting_via_analyzer_and_report(capsys):
    """The two surfaces: MemoryAnalyzer picks n_hosts up from the
    schedule pass's `axis_host_counts` convention (manifest grows the
    per_host block), and debug.memory_report prints the per-host line."""
    import paddle_tpu as paddle
    from paddle_tpu import debug
    from paddle_tpu.analysis.lowering import lower_callable

    program = lower_callable(lambda a: (a * 2.0).sum(),
                             np.zeros((32, 32), np.float32))
    ctx = AnalysisContext(name="hosts",
                          extra={"axis_host_counts": {"dp": 2}})
    report = PassManager(["memory"]).run(program, ctx)
    m = report.metrics["memory"]
    assert m["per_host"]["n_hosts"] == 2
    assert m["per_host"]["peak_bytes"] >= m["peak_bytes"]
    from paddle_tpu.analysis import build_memory_manifest
    assert build_memory_manifest("hosts", report)["per_host"] == \
        m["per_host"]

    paddle.seed(0)
    est = debug.memory_report(lambda a: (a * 2.0).sum(),
                              np.zeros((32, 32), np.float32),
                              axis_host_counts={"dp": 2})
    out = capsys.readouterr().out
    assert est.n_hosts == 2
    assert "per-host peak (2 hosts)" in out


def test_trainer_analysis_program_captures_roles_and_donation():
    """The Trainer front door: per-arg roles/shardings/donation reach
    the passes; donate=False trips MEM-NO-DONATION."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer

    paddle.seed(0)
    build_mesh(dp=len(jax.devices()))
    model = nn.Linear(32, 32)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3)

    def loss_fn(m, batch):
        return (m(paddle.to_tensor(batch["x"])) ** 2).mean()

    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 32).astype("float32")}

    tr = Trainer(model, opt, loss_fn)
    prog = tr.analysis_program(batch)
    roles = {i.role for i in prog.arg_infos}
    assert {"param", "opt_state", "const", "lr", "batch"} <= roles
    batch_args = [i for i in prog.arg_infos if i.role == "batch"]
    assert batch_args and all(i.shard_count == len(jax.devices())
                              for i in batch_args)
    assert all(i.donated for i in prog.arg_infos if i.role == "param")
    pm = PassManager(["memory", "sharding"])
    report = pm.run(prog, AnalysisContext(name="step"))
    assert report.by_rule("MEM-NO-DONATION") == []
    assert report.metrics["memory"]["donated_bytes"] > 0

    tr2 = Trainer(model, opt, loss_fn, donate=False)
    prog2 = tr2.analysis_program(batch)
    report2 = pm.run(prog2, AnalysisContext(name="step"))
    assert report2.by_rule("MEM-NO-DONATION")


def test_kv_cache_donation_lint_planted_defect():
    """MEM-NO-DONATION's decode-loop extension: the serving decoder's
    REAL decode step (cache donated via donate_argnums) lints clean,
    and the planted-defect variant (donate=False — the cache copied
    every step) trips MEM-NO-DONATION-KVCACHE. Params being non-donated
    must NOT fire anything in a decode program: they're read-only
    there, the cache is the carried state."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import PagedGPTDecoder

    paddle.seed(0)
    build_mesh(dp=1)
    model = GPT(gpt_tiny(max_seq_len=64))
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=8, page_size=16, max_batch=2)
    pm = PassManager(["memory"])

    good = dec.analysis_program(donate=True)
    cache_infos = [i for i in good.arg_infos if i.role == "cache"]
    assert cache_infos and all(i.donated for i in cache_infos)
    report = pm.run(good, AnalysisContext(name="decode"))
    assert report.by_rule("MEM-NO-DONATION-KVCACHE") == []
    assert report.by_rule("MEM-NO-DONATION") == []

    # planted defect: same program with the cache's donation dropped
    # (what analysis_program(donate=False) captures — the lint reads
    # arg_infos, so flipping them spares a second trace in tier-1)
    from dataclasses import replace
    infos = [replace(i, donated=False) if i.role == "cache" else i
             for i in good.arg_infos]
    from paddle_tpu.analysis.lowering import LoweredProgram
    defective = LoweredProgram(good.text, jaxpr=good.jaxpr,
                               name="decode_step", arg_infos=infos)
    report2 = pm.run(defective, AnalysisContext(name="decode"))
    hits = report2.by_rule("MEM-NO-DONATION-KVCACHE")
    assert hits and "KV-cache" in hits[0].message
    assert report2.by_rule("MEM-NO-DONATION") == []

    # PARTIAL defect: k_pages donated but v_pages forgotten — half the
    # store still double-buffers, so the rule must check per arg, not
    # any(); the finding names the forgotten bufs
    partial = [replace(i, donated=not (i.name or "").startswith("v_"))
               if i.role == "cache" else i for i in good.arg_infos]
    defective3 = LoweredProgram(good.text, jaxpr=good.jaxpr,
                                name="decode_step", arg_infos=partial)
    report3 = pm.run(defective3, AnalysisContext(name="decode"))
    hits3 = report3.by_rule("MEM-NO-DONATION-KVCACHE")
    assert hits3 and "v_pages" in hits3[0].message
    assert "k_pages" not in hits3[0].message


def test_kv_cache_rule_matches_names_without_role():
    """Programs captured outside serving (raw jit decode loops) are
    still caught by the k_pages/v_pages/cache name heuristic."""
    big = jnp.zeros((4, 64, 64), jnp.float32)

    def step(k_pages, x):
        return k_pages + x, (x * 2.0).sum()

    traced = jax.jit(step).trace(big, big)
    infos = [ArgInfo(name="k_pages", role="input", shape=big.shape,
                     dtype="float32", bytes=big.nbytes),
             ArgInfo(name="x", role="batch", shape=big.shape,
                     dtype="float32", bytes=big.nbytes)]
    from paddle_tpu.analysis.lowering import LoweredProgram
    program = LoweredProgram(traced.lower().as_text(),
                             jaxpr=traced.jaxpr, arg_infos=infos)
    pm = PassManager(["memory"])
    report = pm.run(program, AnalysisContext(name="loop"))
    assert report.by_rule("MEM-NO-DONATION-KVCACHE")
    infos[0].donated = True
    report2 = pm.run(program, AnalysisContext(name="loop"))
    assert report2.by_rule("MEM-NO-DONATION-KVCACHE") == []


def test_debug_memory_report_front_doors(capsys):
    """debug.memory_report works for a Layer and prints the breakdown."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import build_mesh

    paddle.seed(0)
    build_mesh(dp=1)
    model = nn.Linear(16, 16)
    est = paddle.debug.memory_report(model, jnp.zeros((4, 16)))
    out = capsys.readouterr().out
    assert "per-device peak" in out
    assert est.peak_bytes > 0
    assert est.top and est.top[0].device_bytes > 0


def test_propagation_respects_contracted_dot_dims():
    """Sharding propagation fidelity (first slice): a dot_general whose
    operands are sharded ONLY on the contracted dim must not hand that
    shard count to its output (GSPMD all-reduces the partials; the
    result is replicated over that mesh axis). Sharding on a free/batch
    dim still propagates, elementwise chains keep dim knowledge alive,
    and without per-dim info the legacy max-operand heuristic holds."""
    from paddle_tpu.analysis.memory import propagate_shard_counts

    def f(x, w):
        y = x @ w                 # contract dim 1 of x with dim 0 of w
        return (y + 1.0) @ w.T    # elementwise, then contract again

    jx = jax.make_jaxpr(f)(jnp.zeros((8, 16)), jnp.zeros((16, 32))).jaxpr
    dot_out = jx.eqns[0].outvars[0]
    final = jx.outvars[0]

    # legacy (no dim info): the old blind max — unchanged behavior
    legacy = propagate_shard_counts(jx, arg_counts=[4, 4])
    assert legacy[dot_out] == 4

    # contracted-dim sharding (Megatron row-parallel): output replicated
    tp = propagate_shard_counts(jx, arg_counts=[4, 4],
                                arg_dims=[(1, 4), (4, 1)])
    assert tp[dot_out] == 1

    # batch/free-dim sharding (dp): output inherits it — through the
    # elementwise add AND the second matmul (dim 0 stays free)
    dp = propagate_shard_counts(jx, arg_counts=[4, 1],
                                arg_dims=[(4, 1), (1, 1)])
    assert dp[dot_out] == 4 and dp[final] == 4

    # no axis identity in per-dim counts: lhs and rhs free dims sharded
    # 4-way could be the SAME mesh axis, so the 16-way cross product is
    # capped at the most-sharded operand (overestimates memory — the
    # safe direction) instead of claiming shards no mesh has
    capped = propagate_shard_counts(jx, arg_counts=[4, 4],
                                    arg_dims=[(4, 1), (1, 4)])
    assert capped[dot_out] == 4

    # the liveness walk prices with the same rules: a contracted-dim-
    # sharded dot no longer undercounts its output per device
    def g(x, w):
        return x @ w

    traced = jax.jit(g).trace(jnp.zeros((64, 64), jnp.float32),
                              jnp.zeros((64, 64), jnp.float32))
    infos_tp = [
        ArgInfo(name="x", role="batch", shape=(64, 64), dtype="float32",
                bytes=64 * 64 * 4, shard_count=4, dim_shards=(1, 4)),
        ArgInfo(name="w", role="param", shape=(64, 64), dtype="float32",
                bytes=64 * 64 * 4, shard_count=4, dim_shards=(4, 1))]
    infos_blind = [
        ArgInfo(name="x", role="batch", shape=(64, 64), dtype="float32",
                bytes=64 * 64 * 4, shard_count=4),
        ArgInfo(name="w", role="param", shape=(64, 64), dtype="float32",
                bytes=64 * 64 * 4, shard_count=4)]
    est_tp = estimate_jaxpr_memory(traced.jaxpr, arg_infos=infos_tp)
    est_blind = estimate_jaxpr_memory(traced.jaxpr,
                                      arg_infos=infos_blind)
    # blind: output priced at 1/4 (inherited); dim-aware: full size
    assert est_tp.peak_bytes >= est_blind.peak_bytes + 3 * (64 * 64)


def test_propagation_tracks_reshape_split_and_merge():
    """Sharding propagation fidelity (reshape slice): a sharded dim's
    factor follows its factor group through splits and merges when
    divisibility holds, and falls back to the conservative cap (count
    kept, dims dropped) when it doesn't — so dp/tp knowledge survives
    the [B, S, H*D] <-> [B*S, H, D] reshapes between attention
    matmuls instead of dying at the first reshape."""
    from paddle_tpu.analysis.memory import (_reshape_dim_shards,
                                            propagate_shard_counts)

    # --- unit: the factor walk itself
    # split: (32, 16) -> (8, 4, 16), dim0 sharded 4: 4 | 8 -> lands
    # on the group's major dim
    assert _reshape_dim_shards((32, 16), (4, 1), (8, 4, 16)) == \
        (4, 1, 1)
    # merge: (8, 4, 16) -> (32, 16) carries the MAJOR dim's factor
    assert _reshape_dim_shards((8, 4, 16), (4, 1, 1), (32, 16)) == \
        (4, 1)
    # a factor on a MINOR dim of a merge group is a STRIDED pattern of
    # the merged dim — pinning it to the major output dim would move
    # shard knowledge to the wrong dimension (an anti-conservative
    # memory underestimate): bail to the cap instead. Same for two
    # sharded dims in one group (nested blocks, also strided).
    assert _reshape_dim_shards((4, 8, 16), (1, 4, 1), (32, 16)) is None
    assert _reshape_dim_shards((8, 4, 16), (2, 2, 1), (32, 16)) is None
    # non-divisible split: 4-way factor cannot land on a size-2 major
    # dim -> None (caller keeps the conservative cap)
    assert _reshape_dim_shards((6, 16), (4, 1), (2, 3, 16)) is None
    # trailing singleton dims carry nothing
    assert _reshape_dim_shards((32, 16), (4, 1), (32, 16, 1)) == \
        (4, 1, 1)

    # --- through a jaxpr: split -> elementwise -> merge -> contract
    def f(x, w):
        y = x.reshape(8, 4, 16)          # split the dp dim
        y = y + 1.0                      # dim knowledge must survive
        z = y.reshape(32, 16)            # merge it back
        return z @ w                     # contract the LAST dim

    jx = jax.make_jaxpr(f)(jnp.zeros((32, 16)), jnp.zeros((16, 8))).jaxpr
    final = jx.outvars[0]
    # dp on dim 0: factor rides split+merge and survives the dot
    # (dim 0 is a free dim of the contraction)
    dp = propagate_shard_counts(jx, arg_counts=[4, 1],
                                arg_dims=[(4, 1), (1, 1)])
    assert dp[final] == 4
    # sharding on the CONTRACTED dim (dim 1): rides the reshapes, then
    # correctly DROPS at the dot — the dim-aware answer the blind max
    # heuristic can't give
    tp = propagate_shard_counts(jx, arg_counts=[4, 4],
                                arg_dims=[(1, 4), (4, 1)])
    assert tp[final] == 1

    # --- conservative fallback: a non-divisible split keeps the COUNT
    # (max-operand cap) but drops dim knowledge, so the later dot
    # inherits blindly instead of wrongly dropping
    def g(x, w):
        y = x.reshape(2, 3, 16)
        z = y.reshape(6, 16)
        return z @ w

    jx2 = jax.make_jaxpr(g)(jnp.zeros((6, 16)), jnp.zeros((16, 8))).jaxpr
    split_out = jx2.eqns[0].outvars[0]
    # dim 0 (size 6) sharded 4 ways cannot split into (2, 3): the walk
    # bails, dim knowledge is dropped — and with dims unknown even the
    # later contraction inherits blindly (never wrongly drops)
    cons = propagate_shard_counts(jx2, arg_counts=[4, 1],
                                  arg_dims=[(4, 1), (1, 1)])
    assert cons[split_out] == 4          # count kept (safe direction)
    assert cons[jx2.outvars[0]] == 4     # blind inherit at the dot


def test_propagation_drops_reduced_dims():
    """Sharding propagation fidelity (reduce slice): a reduction over a
    SHARDED dim must not hand that shard count to its output — GSPMD
    all-reduces the per-shard partials (reduce_sum is a contraction
    against ones) and the result is replicated over that mesh axis.
    Kept dims thread through; argmax follows the same rule; without
    per-dim info the legacy max-operand heuristic holds."""
    from paddle_tpu.analysis.memory import propagate_shard_counts

    def f(x):
        s = jnp.sum(x, axis=1)        # reduce dim 1
        m = jnp.max(x, axis=0)        # reduce dim 0
        a = jnp.argmax(x, axis=1)     # argmax family: same axes param
        return s + 1.0, m, a          # elementwise keeps dim knowledge

    jx = jax.make_jaxpr(f)(jnp.zeros((8, 16))).jaxpr
    eqns = {e.primitive.name: e for e in jx.eqns}
    s_out = eqns["reduce_sum"].outvars[0]
    m_out = eqns["reduce_max"].outvars[0]
    a_out = eqns["argmax"].outvars[0]
    final_sum = jx.outvars[0]

    # legacy (no dim info): blind max-operand inherit — unchanged
    legacy = propagate_shard_counts(jx, arg_counts=[4])
    assert legacy[s_out] == 4 and legacy[a_out] == 4

    # sharded on dim 1: reducing dim 1 drops the factor (sum AND
    # argmax); reducing dim 0 keeps it; the elementwise chain after
    # the sum stays replicated (dim knowledge survives the reduce)
    tp = propagate_shard_counts(jx, arg_counts=[4], arg_dims=[(1, 4)])
    assert tp[s_out] == 1 and tp[a_out] == 1
    assert tp[m_out] == 4
    assert tp[final_sum] == 1

    # sharded on dim 0: the mirror case
    dp = propagate_shard_counts(jx, arg_counts=[4], arg_dims=[(4, 1)])
    assert dp[s_out] == 4 and dp[a_out] == 4
    assert dp[m_out] == 1

    # full reduction to scalar: every factor drops
    def g(x):
        return jnp.sum(x)

    jx2 = jax.make_jaxpr(g)(jnp.zeros((8, 16))).jaxpr
    full = propagate_shard_counts(jx2, arg_counts=[4],
                                  arg_dims=[(4, 1)])
    assert full[jx2.outvars[0]] == 1

    # no axis identity: a dim-factor product exceeding the most-
    # sharded operand is capped (the dot_general rule, shared)
    capped = propagate_shard_counts(jx, arg_counts=[2],
                                    arg_dims=[(2, 4)])
    assert capped[m_out] <= 2


def test_propagation_drops_scattered_dims():
    """Sharding propagation fidelity (scatter slice): a scatter's
    output has the OPERAND's shape, and the operand's dim sharding
    threads through — EXCEPT on the dynamically indexed dims
    (scatter_dims_to_operand_dims / inserted_window_dims): updates
    land at runtime positions along those dims, so GSPMD cannot keep
    a static split without resharding and the result is at best
    replicated on that mesh axis (the dot/reduce contracted-dim rule
    applied to indexed dims). Capped at the most-sharded operand, as
    everywhere."""
    from paddle_tpu.analysis.memory import (_eqn_out_shard,
                                            propagate_shard_counts)

    def f(x, i, u):
        return x.at[i].set(u), x.at[i].add(u)

    jx = jax.make_jaxpr(f)(jnp.zeros((8, 4)),
                           jnp.zeros((3,), jnp.int32),
                           jnp.zeros((3, 4))).jaxpr
    eqns = {e.primitive.name: e for e in jx.eqns}
    assert "scatter" in eqns and "scatter-add" in eqns

    # --- unit: indexed dim 0 drops its factor, window dim 1 threads
    for name in ("scatter", "scatter-add"):
        cnt, dims = _eqn_out_shard(eqns[name], [8, 1, 1],
                                   [(2, 4), None, None])
        assert cnt == 4 and dims == (1, 4), name
        # operand sharded ONLY on the indexed dim: everything drops
        cnt0, dims0 = _eqn_out_shard(eqns[name], [4, 1, 1],
                                     [(4, 1), None, None])
        assert cnt0 == 1 and dims0 == (1, 1), name
        # cap: kept-dim factor above the most-sharded operand bails to
        # the blind cap (never claim finer sharding than any input)
        cntc, dimsc = _eqn_out_shard(eqns[name], [2, 1, 1],
                                     [(1, 4), None, None])
        assert cntc == 2 and dimsc is None, name
        # legacy (no dim info): blind max-operand inherit — unchanged
        cntl, _ = _eqn_out_shard(eqns[name], [8, 1, 1],
                                 [None, None, None])
        assert cntl == 8, name

    # --- through the jaxpr: dp on the batch dim survives the update
    # (window dim), tp on the indexed dim drops
    counts = propagate_shard_counts(jx, arg_counts=[8, 1, 1],
                                    arg_dims=[(2, 4), None, None])
    set_out = eqns["scatter"].outvars[0]
    add_out = eqns["scatter-add"].outvars[0]
    assert counts[set_out] == 4 and counts[add_out] == 4


def test_propagation_drops_dynamically_indexed_dims():
    """Sharding propagation fidelity (gather/dynamic_slice slice): a
    dim read at DYNAMIC positions (gather's start_index_map, a
    dynamic_slice start) loses its shard factor — rows come from
    runtime positions, so GSPMD cannot keep a static split without
    resharding (the scatter rule's read side) — while dims taken whole
    (full slice size, never index-addressed) thread their factor.
    Capped at the most-sharded operand, as everywhere; without per-dim
    info the legacy max-operand heuristic holds."""
    from paddle_tpu.analysis.memory import (_eqn_out_shard,
                                            propagate_shard_counts)

    def f(x, i):
        g = x[i]                                    # gather rows
        ds = jax.lax.dynamic_slice(
            x, (i[0], 0), (2, 16))                  # dynamic rows
        return g + 0.0, ds

    jx = jax.make_jaxpr(f)(jnp.zeros((32, 16)),
                           jnp.zeros((4,), jnp.int32)).jaxpr
    eqns = {e.primitive.name: e for e in jx.eqns}
    assert "gather" in eqns and "dynamic_slice" in eqns

    # --- unit: the indexed/sliced dim 0 drops its factor, the whole
    # dim 1 threads
    cnt, dims = _eqn_out_shard(eqns["gather"], [8, 1], [(2, 4), None])
    assert cnt == 4 and dims == (1, 4)
    nds = len([v for v in eqns["dynamic_slice"].invars
               if type(v).__name__ != "Literal"])
    cnt, dims = _eqn_out_shard(eqns["dynamic_slice"],
                               [8] + [1] * (nds - 1),
                               [(2, 4)] + [None] * (nds - 1))
    assert cnt == 4 and dims == (1, 4)
    # operand sharded ONLY on the dynamic dim: everything drops
    cnt0, dims0 = _eqn_out_shard(eqns["gather"], [4, 1], [(4, 1), None])
    assert cnt0 == 1 and dims0 == (1, 1)
    cnt0, dims0 = _eqn_out_shard(eqns["dynamic_slice"],
                                 [4] + [1] * (nds - 1),
                                 [(4, 1)] + [None] * (nds - 1))
    assert cnt0 == 1 and dims0 == (1, 1)
    # cap: kept-dim factor above the most-sharded operand bails to the
    # blind cap (never claim finer sharding than any input)
    cntc, dimsc = _eqn_out_shard(eqns["gather"], [2, 1], [(1, 4), None])
    assert cntc == 2 and dimsc is None
    # legacy (no dim info): blind max-operand inherit — unchanged
    cntl, _ = _eqn_out_shard(eqns["gather"], [8, 1], [None, None])
    assert cntl == 8

    # --- through the jaxpr: tp on the embedding dim survives the row
    # gather (and the elementwise chain after it); the dynamic_slice
    # output keeps it too, while the dynamically sliced batch dim's
    # factor is gone from both
    counts = propagate_shard_counts(jx, arg_counts=[8, 1],
                                    arg_dims=[(2, 4), None])
    g_out = eqns["gather"].outvars[0]
    ds_out = eqns["dynamic_slice"].outvars[0]
    assert counts[g_out] == 4 and counts[ds_out] == 4
    assert counts[jx.outvars[0]] == 4


def test_propagation_threads_concat_pad_slice_dims():
    """Sharding propagation fidelity (concatenate/pad/slice slice): the
    structural reshape family threads shard factors through UNTOUCHED
    dims and drops them on the structural ones — the concat dim
    (pieces land at per-operand offsets), padded dims (offsets shift),
    and statically under-sliced or strided dims (the kept span crosses
    shard boundaries) — while a dim every operand agrees on, or one
    taken whole at stride 1, keeps its factor. This is the KV-cache
    idiom (concat new keys on the sequence dim, slice a window): dp/tp
    on the batch/head dims must survive it."""
    from paddle_tpu.analysis.memory import (_eqn_out_shard,
                                            propagate_shard_counts)

    def f(x, y):
        c = jnp.concatenate([x, y], axis=1)     # grow the seq dim
        p = jax.lax.pad(x, 0.0,
                        ((0, 0, 0), (2, 2, 0)))  # pad the seq dim
        sl = jax.lax.slice(c, (0, 0), (8, 4))   # seq window (partial)
        whole = jax.lax.slice(x, (0, 0), (8, 16))   # identity slice
        return c + 0.0, p, sl, whole

    jx = jax.make_jaxpr(f)(jnp.zeros((8, 16)), jnp.zeros((8, 16))).jaxpr
    eqns = {}
    for e in jx.eqns:
        eqns.setdefault(e.primitive.name, []).append(e)
    cat = eqns["concatenate"][0]
    pad = eqns["pad"][0]
    sl_part, sl_whole = eqns["slice"][:2]

    # --- unit: concat dim 1 drops its factor; batch dim 0 threads
    # when every operand agrees
    cnt, dims = _eqn_out_shard(cat, [8, 8], [(2, 4), (2, 4)])
    assert cnt == 2 and dims == (2, 1)
    # operands DISAGREE on the batch factor: that dim drops too
    cnt, dims = _eqn_out_shard(cat, [4, 1], [(4, 1), (1, 1)])
    assert cnt == 1 and dims == (1, 1)
    # --- pad: the padded dim drops, the untouched one threads
    cnt, dims = _eqn_out_shard(pad, [8, 1], [(2, 4), None])
    assert cnt == 2 and dims == (2, 1)
    cnt, dims = _eqn_out_shard(pad, [2, 1], [(2, 1), None])
    assert cnt == 2 and dims == (2, 1)
    # --- slice: a dim taken below full size drops; one taken whole at
    # stride 1 threads
    cnt, dims = _eqn_out_shard(sl_part, [8], [(2, 4)])
    assert cnt == 2 and dims == (2, 1)
    cnt, dims = _eqn_out_shard(sl_whole, [8], [(2, 4)])
    assert cnt == 8 and dims == (2, 4)
    # cap: a kept-dim product above the most-sharded operand bails to
    # the blind cap (never claim finer sharding than any input)
    cntc, dimsc = _eqn_out_shard(sl_whole, [2], [(2, 4)])
    assert cntc == 2 and dimsc is None
    # legacy (no dim info): blind max-operand inherit — unchanged
    cntl, _ = _eqn_out_shard(cat, [8, 8], [None, None])
    assert cntl == 8

    # --- through the jaxpr: dp on the batch dim survives the whole
    # concat -> slice chain (and the elementwise chain after it); the
    # sharded SEQ dim's factor is gone from concat/pad outputs
    counts = propagate_shard_counts(jx, arg_counts=[8, 8],
                                    arg_dims=[(2, 4), (2, 4)])
    assert counts[cat.outvars[0]] == 2
    assert counts[pad.outvars[0]] == 2
    assert counts[sl_part.outvars[0]] == 2
    assert counts[jx.outvars[0]] == 2        # elementwise after concat


def test_propagation_axis_identity_first_slice():
    """Mesh-axis IDENTITY on a dp x tp mesh: seeded vars (entry args
    with a PartitionSpec, sharding_constraint outputs) carry per-dim
    axis NAMES alongside their counts, and `_final_counts` trusts a
    distinct-axes dim product outright instead of capping it at the
    most-sharded operand — the dp x tp cross product is real shards,
    not an over-claim."""
    from jax.sharding import NamedSharding, PartitionSpec
    from paddle_tpu.analysis import propagate_shardings
    from paddle_tpu.analysis.lowering import tree_arg_infos
    from paddle_tpu.distributed import build_mesh

    mesh = build_mesh(dp=2, tp=4)
    jmesh = mesh._mesh if hasattr(mesh, "_mesh") else mesh
    dp_tp = NamedSharding(jmesh, PartitionSpec("dp", "tp"))

    def f(x, w):
        y = x @ w                           # replicated operands
        return jax.lax.with_sharding_constraint(y, dp_tp) + 1.0

    x = jnp.zeros((8, 16), jnp.float32)
    w = jnp.zeros((16, 32), jnp.float32)
    traced = jax.jit(f, in_shardings=(dp_tp, None)).trace(x, w)
    infos = (tree_arg_infos(jax.device_put(x, dp_tp), "batch") +
             tree_arg_infos(w, "param"))
    res = propagate_shardings(traced.jaxpr, arg_infos=infos)
    jx = traced.jaxpr.jaxpr

    # the sharded entry arg is axis-identified; the spec-less one is not
    assert res.axes[jx.invars[0]] == (("dp",), ("tp",))
    assert jx.invars[1] not in res.axes
    # the constraint output carries its NamedSharding's axis names
    cons = [e for e in jx.eqns
            if e.primitive.name == "sharding_constraint"]
    assert cons and res.axes[cons[0].outvars[0]] == (("dp",), ("tp",))
    # the eqn-rule slice carries identity THROUGH the elementwise add:
    # `+ 1.0` inherits the constraint output's axes (the literal is
    # replicated and does not constrain), so 3 vars are identified —
    # the two seeds plus the derived add output
    add = [e for e in jx.eqns if e.primitive.name == "add"]
    assert add and res.axes[add[-1].outvars[0]] == (("dp",), ("tp",))
    assert res.summary()["n_axis_identified"] == 3

    # cap relaxed: both operands replicated (cap would clamp to 1), yet
    # the constraint's distinct dp/tp axes prove the 8-way product
    assert res.counts[cons[0].outvars[0]] == 8

    # identity withheld -> the conservative cap still rules: same
    # program analyzed WITHOUT arg_infos/constraint axes knowledge
    blind = propagate_shardings(traced.jaxpr)
    blind.axes.pop(cons[0].outvars[0], None)
    from paddle_tpu.analysis.propagation import _final_counts
    capped = _final_counts(jx, blind.dims, None, axes=blind.axes)
    assert capped[cons[0].outvars[0]] == 1


def test_propagation_axis_identity_repeated_axis_keeps_cap():
    """Two dims naming the SAME mesh axis do not compose — the spec
    (“dp”, “dp”) is not distinct, so the product cap stays."""
    from paddle_tpu.analysis.propagation import _axes_distinct

    v = object()
    assert _axes_distinct({v: (("dp",), ("tp",))}, v)
    assert not _axes_distinct({v: (("dp",), ("dp",))}, v)
    assert not _axes_distinct({}, v)
    assert _axes_distinct({v: ((), ())}, v)      # replicated is exact


def test_spec_dim_axes_normalization():
    from paddle_tpu.analysis.lowering import spec_dim_axes

    assert spec_dim_axes(None, 2) is None
    assert spec_dim_axes(("dp", None), 2) == (("dp",), ())
    assert spec_dim_axes((("dp", "tp"),), 1) == (("dp", "tp"),)
    # short spec pads with unsharded dims; overlong entries are ignored
    assert spec_dim_axes(("tp",), 3) == (("tp",), (), ())
    assert spec_dim_axes(("a", "b", "c"), 2) == (("a",), ("b",))
