"""LARS optimizer + LocalSGD trainer (reference fleet meta_optimizers:
lars_optimizer.py:21, localsgd_optimizer.py:26)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import build_mesh
from paddle_tpu.distributed.localsgd import LocalSGDTrainer
from paddle_tpu.distributed.trainer import Trainer


def test_lars_momentum_hand_computed():
    paddle.seed(0)
    p0 = np.array([3.0, 4.0], np.float32)       # ||p|| = 5
    g0 = np.array([0.6, 0.8], np.float32)       # ||g|| = 1
    p = paddle.framework.core.Parameter(p0)
    opt = paddle.optimizer.LarsMomentum(
        learning_rate=0.1, momentum=0.9, lars_coeff=0.001,
        lars_weight_decay=0.0005, parameters=[p])
    p.grad = paddle.to_tensor(g0)
    opt.step()
    lars_wd = 0.0005
    local_lr = 0.1 * 0.001 * 5.0 / (1.0 + lars_wd * 5.0)
    v = local_lr * (g0 + lars_wd * p0)
    expected = p0 - v
    np.testing.assert_allclose(p.numpy(), expected, rtol=1e-6)
    # second step exercises momentum accumulation
    p.grad = paddle.to_tensor(g0)
    opt.step()
    p1 = expected
    pn = np.linalg.norm(p1)
    gn = np.linalg.norm(g0)
    llr = 0.1 * 0.001 * pn / (gn + lars_wd * pn)
    v2 = 0.9 * v + llr * (g0 + lars_wd * p1)
    np.testing.assert_allclose(p.numpy(), p1 - v2, rtol=1e-5)


class _MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = paddle.nn.Linear(4, 16)
        self.l2 = paddle.nn.Linear(16, 2)

    def forward(self, x):
        return self.l2(paddle.nn.functional.relu(self.l1(x)))


def _loss_fn(m, batch):
    out = m(paddle.to_tensor(batch["x"]))
    return paddle.nn.functional.cross_entropy(out, paddle.to_tensor(batch["y"]))


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, 4).astype(np.float32),
            "y": rng.randint(0, 2, (n,)).astype(np.int64)}


def test_localsgd_k1_matches_dp_sgd():
    """Sync every step + SGD == plain data-parallel (param-averaging after a
    linear update commutes with grad-averaging)."""
    mesh = build_mesh(dp=8)
    paddle.seed(42)
    m1 = _MLP()
    t_dp = Trainer(m1, paddle.optimizer.SGD(learning_rate=0.1), _loss_fn, mesh=mesh)
    paddle.seed(42)
    m2 = _MLP()
    t_local = LocalSGDTrainer(m2, paddle.optimizer.SGD(learning_rate=0.1),
                              _loss_fn, mesh=mesh, k_steps=1)
    for i in range(3):
        b = _batch(seed=i)
        l1 = float(t_dp.step(b))
        l2 = float(t_local.step(b))
        np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=1e-6)
    t_dp.sync_to_model()
    t_local.sync_to_model()
    for (n1, p1), (n2, p2) in zip(m1.named_parameters(), m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-4, atol=1e-6)


def test_localsgd_diverge_then_sync():
    mesh = build_mesh(dp=8)
    paddle.seed(0)
    model = _MLP()
    tr = LocalSGDTrainer(model, paddle.optimizer.SGD(learning_rate=0.05),
                         _loss_fn, mesh=mesh, k_steps=2)
    tr.step(_batch(seed=1))       # step 1: local only -> ranks diverge
    stack = np.asarray(tr.params["l1.weight"])
    assert not np.allclose(stack[0], stack[1]), "ranks should diverge pre-sync"
    tr.step(_batch(seed=2))       # step 2: sync -> ranks identical
    stack = np.asarray(tr.params["l1.weight"])
    np.testing.assert_allclose(stack[0], stack[-1], rtol=1e-6)


def test_localsgd_trains():
    mesh = build_mesh(dp=8)
    paddle.seed(0)
    model = _MLP()
    tr = LocalSGDTrainer(model, paddle.optimizer.Momentum(learning_rate=0.05),
                         _loss_fn, mesh=mesh, k_steps=4, adaptive=True)
    b = _batch(n=32, seed=3)
    losses = [float(tr.step(b)) for _ in range(12)]
    assert losses[-1] < losses[0], f"no improvement: {losses[0]} -> {losses[-1]}"
