"""Pallas kernels vs jnp reference, interpret mode on CPU (SURVEY §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import _flash, mha_reference
from paddle_tpu.ops.layer_norm import _ln_ref, _rms_ref, fused_layer_norm, fused_rms_norm


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 512, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D), jnp.float32) for _ in range(3))
    out = _flash(q, k, v, causal, 1.0 / np.sqrt(D))
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_kernel_grad():
    rng = np.random.RandomState(1)
    B, L, H, D = 1, 256, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D), jnp.float32) for _ in range(3))

    g1 = jax.grad(lambda q, k, v: jnp.sum(_flash(q, k, v, True, 0.125) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_layer_norm():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256), jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    out = fused_layer_norm(x, w, b)
    ref = _ln_ref(x, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # grads via custom vjp
    g1 = jax.grad(lambda x: jnp.sum(fused_layer_norm(x, w, b) ** 2))(x)
    g2 = jax.grad(lambda x: jnp.sum(_ln_ref(x, w, b, 1e-5) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_fused_rms_norm():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 128), jnp.float32)
    w = jnp.asarray(rng.randn(128), jnp.float32)
    np.testing.assert_allclose(np.asarray(fused_rms_norm(x, w)),
                               np.asarray(_rms_ref(x, w, 1e-6)), atol=1e-5)


def test_fused_ln_odd_shapes_fallback():
    x = jnp.ones((3, 100), jnp.float32)  # h%128 != 0 → reference path
    w = jnp.ones((100,))
    b = jnp.zeros((100,))
    np.testing.assert_allclose(np.asarray(fused_layer_norm(x, w, b)),
                               np.asarray(_ln_ref(x, w, b, 1e-5)), atol=1e-6)


def test_flash_bwd_pallas_matches_xla_vjp():
    """Pallas flash backward (dQ/dKV kernels from saved logsumexp) vs the XLA
    vjp of the jnp reference — both causal and bidirectional."""
    from paddle_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 256, 2, 64
    q = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    k = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    v = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    g = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    for causal in (False, True):
        scale = 1.0 / np.sqrt(D)
        out, lse = A._flash_fwd_lse_impl(q, k, v, causal, scale, interpret=True)
        ref = A.mha_reference(q, k, v, causal=causal, scale=scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        dq, dk, dv = A._flash_bwd_impl(q, k, v, out, lse, g, causal, scale,
                                       interpret=True)
        _, vjp = jax.vjp(lambda q, k, v: A.mha_reference(
            q, k, v, causal=causal, scale=scale), q, k, v)
        rdq, rdk, rdv = vjp(g)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), atol=1e-4)


def test_fused_softmax_cross_entropy():
    """ops/fused_ops.py streaming CE kernel vs logsumexp reference, incl. the
    GPT vocab (50304) whose block divisor is 384."""
    from paddle_tpu.ops.fused_ops import (_xent_fwd_impl, _xent_ref,
                                          fused_softmax_cross_entropy)
    rng = np.random.RandomState(0)
    for n, v in [(256, 1024), (256, 50304)]:
        logits = jnp.asarray(rng.randn(n, v).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, v, n).astype(np.int32))
        loss, _ = _xent_fwd_impl(logits, labels, interpret=True)
        np.testing.assert_allclose(np.asarray(loss),
                                   np.asarray(_xent_ref(logits, labels)),
                                   atol=1e-4)
        grad = jax.grad(lambda l: fused_softmax_cross_entropy(l, labels).sum())(logits)
        ref = jax.nn.softmax(logits, axis=-1) - jax.nn.one_hot(labels, v)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref), atol=1e-5)


def test_fused_adamw_matches_torch():
    import torch
    from paddle_tpu.ops.fused_ops import fused_adamw
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(1000).astype(np.float32))
    g = jnp.asarray(rng.randn(1000).astype(np.float32))
    po, mo, vo = fused_adamw(p, g, jnp.zeros(1000), jnp.zeros(1000),
                             step=1, lr=1e-3, interpret=True)
    tp = torch.tensor(np.asarray(p), requires_grad=True)
    opt = torch.optim.AdamW([tp], lr=1e-3, weight_decay=0.01, eps=1e-8)
    tp.grad = torch.tensor(np.asarray(g))
    opt.step()
    np.testing.assert_allclose(np.asarray(po), tp.detach().numpy(), atol=1e-6)


def test_fused_dropout_residual_layer_norm_eval():
    from paddle_tpu.ops.fused_ops import (_dropout_res_ln_ref,
                                          fused_dropout_residual_layer_norm)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(256, 256).astype(np.float32))
    r = jnp.asarray(rng.randn(256, 256).astype(np.float32))
    w = jnp.asarray(rng.randn(256).astype(np.float32))
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    out_k, h_k = fused_dropout_residual_layer_norm(x, r, w, b, p=0.1,
                                                   training=False, interpret=True)
    out_r, h_r = _dropout_res_ln_ref(x, r, w, b, jax.random.PRNGKey(0),
                                     0.1, 1e-5, False)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_r), atol=1e-6)


def test_fused_dropout_residual_layer_norm_training_path():
    """The TRAINING (dropout) path of the kernel runs in interpret mode
    (mask bits drawn on the host there — the TPU prng primitives have no
    CPU lowering) and its threshold/scale/LN arithmetic matches a golden
    computed from the same bits."""
    from paddle_tpu.ops.fused_ops import fused_dropout_residual_layer_norm
    rng = np.random.RandomState(1)
    n, h, p, seed = 256, 128, 0.3, 5
    x = jnp.asarray(rng.randn(n, h).astype(np.float32))
    r = jnp.asarray(rng.randn(n, h).astype(np.float32))
    w = jnp.asarray(rng.randn(h).astype(np.float32))
    b = jnp.asarray(rng.randn(h).astype(np.float32))
    out_k, h_k = fused_dropout_residual_layer_norm(
        x, r, w, b, p=p, seed=seed, training=True, interpret=True)

    # golden from the identical host bits + the kernel's threshold rule
    bits = np.asarray(jax.random.bits(jax.random.PRNGKey(seed), (n, h),
                                      jnp.uint32))
    keep = bits <= np.uint32((1.0 - p) * (2 ** 32 - 1))
    xd = np.where(keep, np.asarray(x) / (1.0 - p), 0.0)
    hh = xd + np.asarray(r)
    mu = hh.mean(-1, keepdims=True)
    var = ((hh - mu) ** 2).mean(-1, keepdims=True)
    golden = (hh - mu) / np.sqrt(var + 1e-5) * np.asarray(w) + np.asarray(b)
    # dropout actually dropped something, and kept most of the rest
    assert 0.6 < keep.mean() < 0.8
    np.testing.assert_allclose(np.asarray(h_k), hh, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_k), golden, atol=1e-4)


def test_paged_attention_matches_dense():
    """ops/paged_attention.py — paged gather+softmax == dense attention over
    the sequence's actual history, jnp and kernel paths."""
    from paddle_tpu.ops.paged_attention import PagedKVCache, paged_attention
    rng = np.random.RandomState(0)
    H, D, P = 2, 64, 4
    cache = PagedKVCache(16, P, H, D, dtype=jnp.float32)
    hist = {}
    for sid, L in enumerate([6, 3]):
        cache.new_seq(sid)
        hist[sid] = []
        for _ in range(L):
            k = rng.randn(1, H, D).astype(np.float32)
            v = rng.randn(1, H, D).astype(np.float32)
            cache.append(sid, k, v)
            hist[sid].append((k, v))
    table, lens = cache.batch_view([0, 1])
    q = jnp.asarray(rng.randn(2, 1, H, D).astype(np.float32))
    out = paged_attention(q, cache.k_pages, cache.v_pages, table, lens)
    for b in range(2):
        ks = np.concatenate([k for k, _ in hist[b]], 0)
        vs = np.concatenate([v for _, v in hist[b]], 0)
        s = np.einsum("hd,lhd->hl", np.asarray(q[b, 0]), ks) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hl,lhd->hd", p, vs)
        np.testing.assert_allclose(np.asarray(out[b, 0]), ref, atol=1e-5)
    out_k = paged_attention(q, cache.k_pages, cache.v_pages, table, lens,
                            use_kernel=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out), atol=1e-5)


def test_flash_attention_gqa():
    """GQA (Hkv < Hq) via row-folding into the same kernels — fwd + bwd vs
    the repeat-kv reference, causal and bidirectional."""
    from paddle_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    B, L, Hq, Hkv, D = 2, 256, 8, 2, 64
    q = jnp.asarray(rng.randn(B, L, Hq, D).astype(np.float32)) * 0.1
    k = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32)) * 0.1
    v = jnp.asarray(rng.randn(B, L, Hkv, D).astype(np.float32)) * 0.1
    g = jnp.asarray(rng.randn(B, L, Hq, D).astype(np.float32))
    for causal in (False, True):
        sc = 1.0 / np.sqrt(D)
        out, lse = A._flash_fwd_lse_impl(q, k, v, causal, sc, interpret=True)
        ref = A.mha_reference(q, k, v, causal=causal, scale=sc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        dq, dk, dv = A._flash_bwd_impl(q, k, v, out, lse, g, causal, sc,
                                       interpret=True)
        _, vjp = jax.vjp(lambda q, k, v: A.mha_reference(
            q, k, v, causal=causal, scale=sc), q, k, v)
        rdq, rdk, rdv = vjp(g)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), atol=1e-4)


def test_flash_attention_nondivisible_256():
    """Sequences divisible by 128 but not 256 must tile exactly (L=384)."""
    from paddle_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 384, 2, 64).astype(np.float32)) * 0.1
    k = jnp.asarray(rng.randn(1, 384, 2, 64).astype(np.float32)) * 0.1
    v = jnp.asarray(rng.randn(1, 384, 2, 64).astype(np.float32)) * 0.1
    out = A._flash_fwd_impl(q, k, v, True, 0.125, interpret=True)
    ref = A.mha_reference(q, k, v, causal=True, scale=0.125)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_paged_attention_kernel_path(monkeypatch):
    """The scalar-prefetch paged kernel (one page in VMEM per grid step) must
    run — fallback is a test failure here — and match the jnp reference."""
    from paddle_tpu.ops import paged_attention as P

    def no_fallback(name, err):
        raise AssertionError(f"kernel fell back: {err}")
    monkeypatch.setattr(P, "kernel_fallback", no_fallback)

    rng = np.random.RandomState(1)
    B, H, D, page, n_pages = 2, 2, 128, 8, 12
    k_pages = jnp.asarray(rng.randn(n_pages, page, H, D).astype(np.float32))
    v_pages = jnp.asarray(rng.randn(n_pages, page, H, D).astype(np.float32))
    # seq 0 uses pages [3, 5, 7] (len 20), seq 1 uses [2] (len 5)
    table = jnp.asarray(np.array([[3, 5, 7], [2, -1, -1]], np.int32))
    lens = jnp.asarray(np.array([20, 5], np.int32))
    q = jnp.asarray(rng.randn(B, 1, H, D).astype(np.float32))
    out_k = P.paged_attention(q, k_pages, v_pages, table, lens, use_kernel=True)
    out_r = P.paged_attention(q, k_pages, v_pages, table, lens, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=2e-5, atol=2e-5)
