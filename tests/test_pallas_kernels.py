"""Pallas kernels vs jnp reference, interpret mode on CPU (SURVEY §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import _flash, mha_reference
from paddle_tpu.ops.layer_norm import _ln_ref, _rms_ref, fused_layer_norm, fused_rms_norm


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 512, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D), jnp.float32) for _ in range(3))
    out = _flash(q, k, v, causal, 1.0 / np.sqrt(D))
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_kernel_grad():
    rng = np.random.RandomState(1)
    B, L, H, D = 1, 256, 2, 64
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D), jnp.float32) for _ in range(3))

    g1 = jax.grad(lambda q, k, v: jnp.sum(_flash(q, k, v, True, 0.125) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_layer_norm():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256), jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    out = fused_layer_norm(x, w, b)
    ref = _ln_ref(x, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # grads via custom vjp
    g1 = jax.grad(lambda x: jnp.sum(fused_layer_norm(x, w, b) ** 2))(x)
    g2 = jax.grad(lambda x: jnp.sum(_ln_ref(x, w, b, 1e-5) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_fused_rms_norm():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 128), jnp.float32)
    w = jnp.asarray(rng.randn(128), jnp.float32)
    np.testing.assert_allclose(np.asarray(fused_rms_norm(x, w)),
                               np.asarray(_rms_ref(x, w, 1e-6)), atol=1e-5)


def test_fused_ln_odd_shapes_fallback():
    x = jnp.ones((3, 100), jnp.float32)  # h%128 != 0 → reference path
    w = jnp.ones((100,))
    b = jnp.zeros((100,))
    np.testing.assert_allclose(np.asarray(fused_layer_norm(x, w, b)),
                               np.asarray(_ln_ref(x, w, b, 1e-5)), atol=1e-6)


def test_flash_bwd_pallas_matches_xla_vjp():
    """Pallas flash backward (dQ/dKV kernels from saved logsumexp) vs the XLA
    vjp of the jnp reference — both causal and bidirectional."""
    from paddle_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 256, 2, 64
    q = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    k = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    v = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32)) * 0.1
    g = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    for causal in (False, True):
        scale = 1.0 / np.sqrt(D)
        out, lse = A._flash_fwd_lse_impl(q, k, v, causal, scale, interpret=True)
        ref = A.mha_reference(q, k, v, causal=causal, scale=scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        dq, dk, dv = A._flash_bwd_impl(q, k, v, out, lse, g, causal, scale,
                                       interpret=True)
        _, vjp = jax.vjp(lambda q, k, v: A.mha_reference(
            q, k, v, causal=causal, scale=scale), q, k, v)
        rdq, rdk, rdv = vjp(g)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), atol=1e-4)
