"""Planted-defect proofs for the Graph Doctor rules: each test builds a
program WITH a known performance defect and asserts the right analyzer
catches it (and that the healthy twin stays clean) — the acceptance
bar for trusting the lint gate's green.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.analysis import (AnalysisContext, LoweredProgram,
                                 PassManager, Severity, lower_callable,
                                 lower_layer)
from paddle_tpu.distributed import build_mesh
from paddle_tpu.framework.core import apply_op


def _graph_pm():
    return PassManager(["layout", "dtype", "host-transfer",
                        "graph-shape", "collective"])


# ---------------------------------------------------------------- layout

class _ConvNet(nn.Layer):
    """NHWC conv stack; with `defect` an NCHW round-trip is planted
    between the convs (the exact pattern that cost ~15x on ResNet)."""

    def __init__(self, defect):
        super().__init__()
        self.c1 = nn.Conv2D(3, 8, 3, padding=1, data_format="NHWC")
        self.c2 = nn.Conv2D(8, 8, 3, padding=1, data_format="NHWC")
        self._defect = defect

    def forward(self, x):
        x = self.c1(x)
        if self._defect:
            x = apply_op(lambda v: jnp.transpose(v, (0, 3, 1, 2)), x)
            x = apply_op(lambda v: jnp.transpose(v, (0, 2, 3, 1)), x)
        return self.c2(x)


def test_layout_rule_catches_planted_body_transpose():
    paddle.seed(0)
    build_mesh(dp=1)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    ctx = AnalysisContext(name="convnet", data_format="NHWC")

    clean = _graph_pm().run(lower_layer(_ConvNet(False), x), ctx)
    assert clean.by_rule("LAYOUT-ACT-TRANSPOSE") == []

    bad = _graph_pm().run(lower_layer(_ConvNet(True), x), ctx)
    hits = bad.by_rule("LAYOUT-ACT-TRANSPOSE")
    assert len(hits) == 2, [str(f) for f in bad.findings]
    assert all(f.severity == Severity.ERROR for f in hits)
    assert "NHWC" in hits[0].suggested_fix


class _InputTransposeNet(nn.Layer):
    """The sneakiest layout defect: transposing the INPUT image itself.
    In the lowered functional form the input is also a %arg, so a
    naive applied-to-%arg exemption would misread it as a free weight-
    layout move — the program's input_arg_ids must catch it."""

    def __init__(self):
        super().__init__()
        self.c1 = nn.Conv2D(3, 8, 3, padding=1, data_format="NHWC")

    def forward(self, x):
        x = apply_op(lambda v: jnp.transpose(v, (0, 2, 1, 3)), x)
        return self.c1(x)


def test_layout_rule_catches_input_arg_transpose():
    paddle.seed(0)
    build_mesh(dp=1)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    program = lower_layer(_InputTransposeNet(), x)
    assert program.input_arg_ids, "lower_layer lost input arg tracking"
    report = _graph_pm().run(program, AnalysisContext(
        name="input_t", data_format="NHWC"))
    hits = report.by_rule("LAYOUT-ACT-TRANSPOSE")
    assert hits and hits[0].severity == Severity.ERROR, \
        [str(f) for f in report.findings]
    # the jit front door sees it too: to_static(lint=True) must thread
    # input arg ids through to the same classification
    paddle.seed(0)
    sf = paddle.jit.to_static(_InputTransposeNet(), lint=True)
    with pytest.warns(UserWarning):
        sf(paddle.to_tensor(np.zeros((2, 16, 16, 3), "float32")))
    assert sf.lint_report.by_rule("LAYOUT-ACT-TRANSPOSE")


# ----------------------------------------------------------------- dtype

class _MatNet(nn.Layer):
    """bf16 linear; the defect runs the matmul in f32 via a raw jnp op
    (the amp_compute_cast rule would neutralize a plain astype before
    nn.Linear — which is itself worth knowing: the planted defect must
    bypass amp exactly like a hand-rolled kernel would)."""

    def __init__(self, defect):
        super().__init__()
        self.fc = nn.Linear(16, 16)
        self._defect = defect

    def forward(self, x):
        if self._defect:
            return apply_op(
                lambda v, w: (v.astype(jnp.float32)
                              @ w.astype(jnp.float32)),
                x, self.fc.weight)
        return self.fc(x)


def test_dtype_rule_catches_planted_f32_upcast():
    paddle.seed(0)
    build_mesh(dp=1)
    ctx = AnalysisContext(name="matnet", policy_dtype="bfloat16")
    x = jnp.zeros((4, 16), jnp.bfloat16)

    clean_model = _MatNet(False)
    clean_model.bfloat16()
    clean = _graph_pm().run(lower_layer(clean_model, x), ctx)
    assert clean.by_rule("DTYPE-F32-MATMUL") == []

    bad_model = _MatNet(True)
    bad_model.bfloat16()
    bad = _graph_pm().run(lower_layer(bad_model, x), ctx)
    hits = bad.by_rule("DTYPE-F32-MATMUL")
    # the planted upcast promotes the matmul: amp_compute_cast would
    # normally down-cast, so the defect plants the cast INSIDE the op's
    # operand set — at least the poisoned dot must be flagged
    assert hits, [str(f) for f in bad.findings]
    assert all(f.severity == Severity.ERROR for f in hits)


def test_dtype_rule_honors_router_exemption():
    """An f32 dot is an ERROR unless the context's f32_dot_allow
    blesses it (the MoE router rule)."""
    def f(x, w):
        return x.astype(jnp.float32) @ w.astype(jnp.float32)

    program = lower_callable(f, jnp.zeros((4, 8), jnp.bfloat16),
                             jnp.zeros((8, 4), jnp.bfloat16),
                             name="router")
    strict = _graph_pm().run(program, AnalysisContext(
        policy_dtype="bfloat16"))
    assert strict.by_rule("DTYPE-F32-MATMUL")
    lax_ctx = AnalysisContext(policy_dtype="bfloat16",
                              f32_dot_allow=lambda op: True)
    blessed = _graph_pm().run(program, lax_ctx)
    assert blessed.by_rule("DTYPE-F32-MATMUL") == []
    assert blessed.by_rule("DTYPE-F32-ALLOWED")


# --------------------------------------------------------- host transfer

def test_host_transfer_rule_catches_debug_callback():
    def bad(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    program = lower_callable(bad, jnp.zeros((4,)), name="cb")
    report = _graph_pm().run(program, AnalysisContext())
    hits = report.by_rule("HOST-CALLBACK")
    assert hits and hits[0].severity == Severity.ERROR
    assert report.metrics["host-transfer"]["n_host_callbacks"] >= 1

    def clean(x):
        return x * 2

    report = _graph_pm().run(lower_callable(clean, jnp.zeros((4,))),
                             AnalysisContext())
    assert report.by_rule("HOST-CALLBACK") == []


# ----------------------------------------------------------- graph shape

def test_graph_shape_rule_catches_opcount_and_double_forward():
    def once(x, w):
        return x @ w

    def twice(x, w):
        # the duplicate-forward defect: the same matmul materialized
        # twice (lost CSE / broken remat shows up exactly like this)
        return x @ w + jnp.sin(x @ w + 1.0)

    args = (jnp.zeros((4, 8)), jnp.zeros((8, 4)))
    p1 = lower_callable(once, *args, name="once")
    p2 = lower_callable(twice, *args, name="twice")

    ok = _graph_pm().run(p1, AnalysisContext(
        expected_counts={"dot_general": 1}))
    assert ok.by_rule("GRAPH-OPCOUNT-DRIFT") == []

    drift = _graph_pm().run(p2, AnalysisContext(
        expected_counts={"dot_general": 1}))
    assert drift.by_rule("GRAPH-OPCOUNT-DRIFT")

    # manifest drift + the doubled-MXU-op heuristic
    manifest = {"op_counts": {"dot_general": 1}}
    rep = _graph_pm().run(p2, AnalysisContext(manifest=manifest))
    assert rep.by_rule("GRAPH-MANIFEST-DRIFT")
    assert rep.by_rule("GRAPH-DOUBLE-FORWARD")


# ------------------------------------------------------------ collective

def test_collective_rule_counts_payload_and_cross_checks_cost_model():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.cost_model import collective_wire_bytes

    n_dev = len(jax.devices())
    mesh = build_mesh(dp=n_dev)   # conftest pins an 8-device CPU mesh

    def allreduce(x):
        return jax.lax.psum(x, "dp")

    fn = shard_map(allreduce, mesh=mesh, in_specs=P("dp"),
                   out_specs=P())
    program = lower_callable(fn, jnp.zeros((n_dev, 4), jnp.float32),
                             name="psum")
    report = _graph_pm().run(program, AnalysisContext(
        mesh_axes={"dp": n_dev}))
    coll = report.metrics["collective"]
    assert coll["n_collectives"] == 1
    entry = coll["collectives"][0]
    assert entry["op"] == "all_reduce"
    # per-shard payload: 1x4 f32 = 16 bytes
    assert entry["payload_bytes"] == 16
    assert entry["group_size"] == n_dev
    assert entry["wire_bytes"] == collective_wire_bytes(
        "all_reduce", 16, n_dev)
    assert entry["mesh_axis"] == "dp"
    assert report.metrics["collective"]["per_mesh_axis"]["dp"]["count"] == 1
    # tiny payload -> bucketing advice
    assert report.by_rule("COLL-TINY-PAYLOAD")

    # the same program pinned single-device is an ERROR
    pinned = _graph_pm().run(program, AnalysisContext(
        expect_collectives=False))
    assert pinned.by_rule("COLL-UNEXPECTED")
    assert pinned.errors

    # all_gather: the OPERAND is the 1/n shard but the ring moves
    # (n-1)/n of the FULL gathered payload — the analyzer must feed the
    # result (full) size into the cost model, not the shard size
    def gather(x):
        return jax.lax.all_gather(x, "dp")

    g_fn = shard_map(gather, mesh=mesh, in_specs=P("dp"),
                     out_specs=P("dp"))
    g_prog = lower_callable(g_fn, jnp.zeros((n_dev, 4), jnp.float32),
                            name="gather")
    g_rep = _graph_pm().run(g_prog, AnalysisContext())
    entries = [e for e in g_rep.metrics["collective"]["collectives"]
               if e["op"] == "all_gather"]
    assert entries, g_rep.metrics["collective"]
    e = entries[0]
    full = n_dev * 4 * 4          # gathered [n_dev, 4] f32
    assert e["wire_bytes"] == collective_wire_bytes(
        "all_gather", full, n_dev) == int(full * (n_dev - 1) / n_dev)


def test_collective_axis_attribution_disambiguates_equal_sizes():
    """On a square mesh two axes share a group SIZE; only the device-id
    stride of the replica groups tells them apart — tp (innermost,
    stride 1) vs dp (stride = tp size)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(dp=2, tp=2, devices=jax.devices()[:4])

    def body(x):
        a = jax.lax.psum(x, "tp")
        return jax.lax.psum(a, "dp")

    fn = shard_map(body, mesh=mesh, in_specs=P("dp", "tp"),
                   out_specs=P())
    program = lower_callable(fn, jnp.zeros((4, 8), jnp.float32),
                             name="square")
    report = _graph_pm().run(program, AnalysisContext(
        mesh_axes={"dp": 2, "tp": 2}))
    axes = [e["mesh_axis"] for e in
            report.metrics["collective"]["collectives"]]
    assert sorted(a for a in axes if a) == ["dp", "tp"], (
        axes, report.metrics["collective"]["collectives"])


def test_collective_wire_bytes_model():
    from paddle_tpu.cost_model import collective_wire_bytes
    # ring all-reduce moves 2(n-1)/n of the payload per device
    assert collective_wire_bytes("all_reduce", 1024, 8) == \
        int(1024 * 2 * 7 / 8)
    assert collective_wire_bytes("all_gather", 1024, 8) == \
        int(1024 * 7 / 8)
    assert collective_wire_bytes("all_reduce", 1024, 1) == 0


def test_collective_wire_bytes_edge_cases_and_aliases():
    """Degenerate groups are free; reduce_scatter/all_to_all have ring
    formulas; jaxpr primitive names alias to their HLO collectives so
    the sharding pass can price every collective either walk emits."""
    from paddle_tpu.cost_model import collective_wire_bytes as w
    # group_size=1 (or absent/invalid) folds to a copy: zero wire bytes
    assert w("all_gather", 4096, 1) == 0
    assert w("reduce_scatter", 4096, None) == 0
    assert w("all_to_all", 4096, 0) == 0
    assert w("all_reduce", 0, 8) == 0
    assert w("all_reduce", None, 8) == 0
    # full-payload ring formulas
    assert w("reduce_scatter", 4096, 8) == int(4096 * 7 / 8)
    assert w("all_to_all", 4096, 8) == int(4096 * 7 / 8)
    assert w("collective_permute", 4096, 8) == 4096
    # jaxpr-name aliases agree with their HLO lowerings
    assert w("psum", 4096, 8) == w("all_reduce", 4096, 8)
    assert w("ppermute", 4096, 8) == w("collective_permute", 4096, 8)
    assert w("psum_scatter", 4096, 8) == w("reduce_scatter", 4096, 8)


# -------------------------------------------------------------- sharding

def _info(name, role, shape, shard_count, itemsize=4):
    import numpy as np
    from paddle_tpu.analysis import ArgInfo
    return ArgInfo(name=name, role=role, shape=tuple(shape),
                   dtype="float32",
                   bytes=int(np.prod(shape)) * itemsize,
                   shard_count=shard_count)


def _sharding_pm():
    return PassManager(["sharding"])


def test_sharding_rule_catches_replicated_param_under_fsdp():
    """A big replicated param on an fsdp mesh is the ZeRO promise broken
    — ERROR; the sharded twin stays clean."""
    program = LoweredProgram("", name="synthetic")
    ctx = AnalysisContext(name="synthetic", mesh_axes={"fsdp": 8})

    program.arg_infos = [_info("w", "param", (1024, 1024), 1)]
    bad = _sharding_pm().run(program, ctx)
    hits = bad.by_rule("SHARD-REPLICATED-BIG")
    assert hits and hits[0].severity == Severity.ERROR
    assert bad.metrics["sharding"]["n_replicated_big"] == 1

    program.arg_infos = [_info("w", "param", (1024, 1024), 8)]
    clean = _sharding_pm().run(program, ctx)
    assert clean.by_rule("SHARD-REPLICATED-BIG") == []
    # small replicated tensors never fire (below the threshold)
    program.arg_infos = [_info("b", "param", (128,), 1)]
    small = _sharding_pm().run(program, ctx)
    assert small.by_rule("SHARD-REPLICATED-BIG") == []
    # replication under a dp-only mesh is by design — no finding
    program.arg_infos = [_info("w", "param", (1024, 1024), 1)]
    dp_only = _sharding_pm().run(
        program, AnalysisContext(mesh_axes={"dp": 8}))
    assert dp_only.by_rule("SHARD-REPLICATED-BIG") == []


def test_sharding_rule_catches_unsharded_opt_state():
    """Optimizer slots replicated while their same-shape param is
    sharded: the silent 2-3x HBM leak the ZeRO configs exist to kill."""
    program = LoweredProgram("", name="synthetic")
    ctx = AnalysisContext(name="synthetic", mesh_axes={"fsdp": 8})

    program.arg_infos = [
        _info("w", "param", (1024, 1024), 8),
        _info("slots/w/moment1", "opt_state", (1024, 1024), 1),
    ]
    bad = _sharding_pm().run(program, ctx)
    hits = bad.by_rule("SHARD-OPT-STATE-UNSHARDED")
    assert hits and hits[0].severity == Severity.ERROR
    assert "moment1" in hits[0].message

    program.arg_infos = [
        _info("w", "param", (1024, 1024), 8),
        _info("slots/w/moment1", "opt_state", (1024, 1024), 8),
    ]
    clean = _sharding_pm().run(program, ctx)
    assert clean.by_rule("SHARD-OPT-STATE-UNSHARDED") == []


def test_sharding_rule_catches_mid_program_reshard():
    """A planted ppermute lowers to collective_permute — the signature
    of a GSPMD spec mismatch; the exemption regex silences by-design
    dispatch."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_dev = len(jax.devices())
    mesh = build_mesh(dp=n_dev)

    def shift(x):
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        return jax.lax.ppermute(x, "dp", perm)

    fn = shard_map(shift, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    program = lower_callable(fn, jnp.zeros((n_dev, 8), jnp.float32),
                             name="shift")
    report = _sharding_pm().run(program, AnalysisContext(
        mesh_axes={"dp": n_dev}))
    hits = report.by_rule("SHARD-MID-PROGRAM-RESHARD")
    assert hits and hits[0].severity == Severity.WARNING
    assert report.metrics["sharding"]["n_mid_program_reshards"] == 1

    blessed = _sharding_pm().run(program, AnalysisContext(
        mesh_axes={"dp": n_dev},
        allowed_resharding=(r"collective_permute",)))
    assert blessed.by_rule("SHARD-MID-PROGRAM-RESHARD") == []

    # a collective-free program never fires
    clean_prog = lower_callable(lambda x: x * 2,
                                jnp.zeros((8,), jnp.float32))
    clean = _sharding_pm().run(clean_prog, AnalysisContext())
    assert clean.by_rule("SHARD-MID-PROGRAM-RESHARD") == []


def test_sharding_rule_catches_wire_byte_regression():
    """Total analytic wire bytes above the committed memory manifest's
    pin is an ERROR (a collective grew or a new one appeared)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.cost_model import collective_wire_bytes

    n_dev = len(jax.devices())
    mesh = build_mesh(dp=n_dev)

    def allreduce(x):
        return jax.lax.psum(x, "dp")

    fn = shard_map(allreduce, mesh=mesh, in_specs=P("dp"), out_specs=P())
    program = lower_callable(fn, jnp.zeros((n_dev, 1024), jnp.float32),
                             name="psum")
    fresh = _sharding_pm().run(program, AnalysisContext())
    wire = fresh.metrics["sharding"]["total_wire_bytes"]
    # per-shard [1,1024] f32 is both operand and result of the psum
    assert wire == collective_wire_bytes("all_reduce", 1024 * 4, n_dev)

    # committed manifest pinned half the volume -> regression fires
    ctx = AnalysisContext(memory_manifest={
        "collectives": {"total_wire_bytes": wire // 2}})
    bad = _sharding_pm().run(program, ctx)
    assert bad.by_rule("SHARD-WIRE-REGRESSION")
    # pinned at the current volume -> clean
    ctx = AnalysisContext(memory_manifest={
        "collectives": {"total_wire_bytes": wire}})
    ok = _sharding_pm().run(program, ctx)
    assert ok.by_rule("SHARD-WIRE-REGRESSION") == []


# ----------------------------------------------------- jit / to_static

def test_to_static_lint_populates_report(tmp_path):
    """to_static(lint=True): graph findings appear on .lint_report after
    the first call (the planted f32 upcast is visible through the jit
    wrapper too)."""
    paddle.seed(0)
    build_mesh(dp=1)
    model = _MatNet(True)
    model.bfloat16()
    sf = paddle.jit.to_static(model, lint=True)
    with pytest.warns(UserWarning):
        sf(paddle.to_tensor(np.zeros((4, 16), "float32")).astype(
            "bfloat16"))
    assert sf.lint_report is not None
    assert sf.lint_report.by_rule("DTYPE-F32-MATMUL")


def test_debug_diagnose_entry_point():
    paddle.seed(0)
    build_mesh(dp=1)
    model = _ConvNet(True)
    report = paddle.debug.diagnose(
        model, jnp.zeros((2, 16, 16, 3), jnp.float32),
        context=AnalysisContext(name="convnet", data_format="NHWC"),
        print_report=False)
    assert report.by_rule("LAYOUT-ACT-TRANSPOSE")


# -------------------------------------------------- serving decode loop

def test_serving_rule_catches_undonated_cache_in_fused_loop():
    """SERVE-HOST-SYNC-DECODE planted defect: the fused decode_multi
    program with cache donation dropped (analysis_program(donate=False,
    k=...)) is an ERROR — every K-tick horizon would copy the whole
    paged KV store. The real capture (donated) stays clean, and the
    rule is scoped: without extra["serving_decode"] it never fires."""
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import PagedGPTDecoder

    paddle.seed(0)
    build_mesh(dp=1)
    model = GPT(gpt_tiny(max_seq_len=64, dtype="float32", remat=False))
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=8, page_size=16, max_batch=2)
    pm = PassManager(["serving"])
    ctx = AnalysisContext(name="decode", extra={"serving_decode": True})

    good = dec.analysis_program(donate=True, k=2)
    report = pm.run(good, ctx)
    assert report.by_rule("SERVE-HOST-SYNC-DECODE") == []
    assert report.metrics["serving"]["cache_donated"]
    assert report.metrics["serving"]["n_device_loops"] >= 1

    bad = dec.analysis_program(donate=False, k=2)
    report2 = pm.run(bad, ctx)
    hits = report2.by_rule("SERVE-HOST-SYNC-DECODE")
    assert hits and hits[0].severity == Severity.ERROR
    assert "KV-cache" in hits[0].message

    # scope: the same defective program outside a serving context is
    # not this rule's business (MEM-NO-DONATION-KVCACHE still warns)
    report3 = pm.run(bad, AnalysisContext(name="decode"))
    assert report3.by_rule("SERVE-HOST-SYNC-DECODE") == []
    assert report3.metrics["serving"] == {"checked": False}


def test_serving_rule_catches_host_callback_in_fused_loop():
    """A host callback smuggled into a device-resident decode loop is
    the per-tick round-trip the fused program exists to kill."""
    def fused_loop_with_callback(tokens, k_pages):
        def tick(carry, _):
            t, kp = carry
            jax.debug.print("tick {t}", t=t)     # the planted defect
            t = t + 1
            kp = kp + 1.0
            return (t, kp), t
        (tokens, k_pages), _ = jax.lax.scan(
            tick, (tokens, k_pages), jnp.arange(4))
        return tokens, k_pages

    program = lower_callable(fused_loop_with_callback,
                             jnp.zeros((2,), jnp.int32),
                             jnp.zeros((4, 8), jnp.float32),
                             name="decode_multi")
    pm = PassManager(["serving"])
    ctx = AnalysisContext(name="decode", extra={"serving_decode": True})
    report = pm.run(program, ctx)
    hits = report.by_rule("SERVE-HOST-SYNC-DECODE")
    assert hits and any("host transfer" in h.message for h in hits)
    assert report.metrics["serving"]["n_host_transfers"] >= 1

    def clean_loop(tokens, k_pages):
        def tick(carry, _):
            t, kp = carry
            return (t + 1, kp + 1.0), t
        (tokens, k_pages), _ = jax.lax.scan(
            tick, (tokens, k_pages), jnp.arange(4))
        return tokens, k_pages

    clean = lower_callable(clean_loop, jnp.zeros((2,), jnp.int32),
                           jnp.zeros((4, 8), jnp.float32),
                           name="decode_multi")
    report2 = pm.run(clean, ctx)
    # name-matched k_pages arg is undonated in this raw capture — only
    # the cache finding may fire, never a host-transfer one
    assert all("KV-cache" in h.message
               for h in report2.by_rule("SERVE-HOST-SYNC-DECODE"))
    assert report2.metrics["serving"]["n_host_transfers"] == 0


def test_roofline_drift_rule_planted_mispricing():
    """ROOFLINE-DRIFT planted defect: a drift report whose measured
    horizon times track the priced roofline audits clean; a
    deliberately MISPRICED dispatch shape (measured 10x the priced
    max(compute, HBM, wire)) is the silent-scheduling-error class and
    an ERROR; an overpriced shape (capacity left idle) is a WARNING.
    Without extra["roofline_drift"] the rule never fires."""
    program = lower_callable(lambda x: x + 1.0,
                             jnp.zeros((2,), jnp.float32), name="decode")
    pm = PassManager(["roofline-drift"])

    def entry(shape, pred, meas, n=8):
        return {"shape": list(shape), "n": n, "predicted_s": pred,
                "measured_s": meas, "ratio": meas / pred}

    clean = [entry(("ragged", 8, 16), 1e-3, 1.4e-3),
             entry(("decode", 8, 1), 8e-4, 9e-4),
             # under the sample floor: one cold tick is noise
             entry(("ragged", 1, 1), 1e-3, 99.0, n=1)]
    report = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": clean}))
    assert report.by_rule("ROOFLINE-DRIFT") == []
    m = report.metrics["roofline-drift"]
    assert m["checked"] and m["n_checked"] == 2 and m["n_over"] == 0

    planted = clean + [entry(("ragged", 8, 64), 1e-3, 1e-2)]
    report2 = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": planted}))
    hits = report2.by_rule("ROOFLINE-DRIFT")
    assert hits and hits[0].severity == Severity.ERROR
    assert "ragged" in hits[0].message and "10.0x over" in hits[0].message
    assert report2.metrics["roofline-drift"]["n_over"] == 1

    # overpriced: schedulable capacity left on the table -> WARNING
    over = [entry(("train", 4), 1e-2, 1e-3)]
    report3 = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": over}))
    hits3 = report3.by_rule("ROOFLINE-DRIFT")
    assert hits3 and hits3[0].severity == Severity.WARNING
    assert "UNDER" in hits3[0].message

    # the factor is configurable: the same mispriced shape passes a
    # loose factor
    report4 = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": planted, "drift_factor": 20}))
    assert report4.by_rule("ROOFLINE-DRIFT") == []

    # scope: no drift report on the context -> not this rule's business
    report5 = pm.run(program, AnalysisContext(name="s"))
    assert report5.by_rule("ROOFLINE-DRIFT") == []
    assert report5.metrics["roofline-drift"] == {"checked": False}


def test_roofline_drift_fires_on_live_recorder_ledger():
    """The rule consumes exactly what the flight recorder emits: a
    FlightRecorder fed a mispriced dispatch (tick_complete measured far
    over predicted_s) produces a drift_report() the analyzer flags,
    red→green once the pricing is fixed."""
    from paddle_tpu.serving import FlightRecorder
    program = lower_callable(lambda x: x + 1.0,
                             jnp.zeros((2,), jnp.float32), name="decode")
    pm = PassManager(["roofline-drift"])

    def ledger(pred):
        rec = FlightRecorder()
        for _ in range(4):
            rec.tick("serve", ("ragged", 4, 8), measured_s=4e-3,
                     predicted_s=pred, k=4, w=8)
        return rec.drift_report()

    bad = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": ledger(1e-4)}))
    assert bad.by_rule("ROOFLINE-DRIFT"), "mispriced ledger not caught"
    good = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": ledger(3e-3)}))
    assert good.by_rule("ROOFLINE-DRIFT") == []


def test_prefill_stall_rule_audits_schedule_trace():
    """SERVE-PREFILL-STALL planted defect: a scheduling trace whose
    prompts all streamed in as horizon chunks (or whose only blocking
    prefill found an idle batch) audits clean; a host-blocking prefill
    dispatched while decode slots were live is the stall and an ERROR.
    Without extra["serve_schedule"] the rule never fires."""
    program = lower_callable(lambda x: x + 1.0,
                             jnp.zeros((2,), jnp.float32), name="decode")
    pm = PassManager(["prefill-stall"])
    clean = [
        {"kind": "horizon", "k": 4, "w": 8, "decode_rows": 1,
         "prefill_rows": 1},
        {"kind": "horizon", "k": 8, "w": 1, "decode_rows": 2,
         "prefill_rows": 0},
        # a blocking prefill into an EMPTY batch stalls nobody — the
        # cold-start case every engine pays once
        {"kind": "prefill_sync", "decode_active": 0, "rows": 2},
    ]
    report = pm.run(program, AnalysisContext(
        name="s", extra={"serve_schedule": clean}))
    assert report.by_rule("SERVE-PREFILL-STALL") == []
    m = report.metrics["prefill-stall"]
    assert m["checked"] and m["n_mixed_horizons"] == 1
    assert m["n_stalled_prefill_syncs"] == 0

    planted = clean + [{"kind": "prefill_sync", "decode_active": 3,
                        "rows": 1}]
    report2 = pm.run(program, AnalysisContext(
        name="s", extra={"serve_schedule": planted}))
    hits = report2.by_rule("SERVE-PREFILL-STALL")
    assert hits and hits[0].severity == Severity.ERROR
    assert "3 running decode slot" in hits[0].message
    assert report2.metrics["prefill-stall"]["n_stalled_prefill_syncs"] == 1

    # scope: no trace on the context -> not this rule's business
    report3 = pm.run(program, AnalysisContext(name="s"))
    assert report3.by_rule("SERVE-PREFILL-STALL") == []
    assert report3.metrics["prefill-stall"] == {"checked": False}


def test_prefill_stall_traces_from_real_engines():
    """The engines emit the traces the rule audits: the dispatch-
    separate baseline admitting a prompt while another slot decodes
    logs a stalled prefill_sync (the rule fires on its trace); the
    ragged engine's trace for the same workload has chunked horizons
    and audits clean."""
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder

    paddle.seed(3)
    build_mesh(dp=1)
    model = GPT(gpt_tiny(max_seq_len=64, dtype="float32", remat=False))
    model.eval()
    pm = PassManager(["prefill-stall"])
    program = lower_callable(lambda x: x + 1.0,
                             jnp.zeros((2,), jnp.float32), name="decode")

    # the canonical stall, staged deterministically on the blocking
    # path: one slot is mid-decode when a long prompt arrives and its
    # whole prefill dispatches as ONE blocking forward
    dec = PagedGPTDecoder(model, num_pages=16, page_size=16, max_batch=2)
    base = ContinuousBatchingEngine(dec, max_new_tokens=10, k_max=1)
    base.submit(np.asarray([1, 2, 3], np.int32))
    base.step()
    base.step()                      # slot 0 decoding
    base.submit(np.asarray(list(range(1, 25)), np.int32))
    base.step()                      # blocking prefill, decode live
    report = pm.run(program, AnalysisContext(
        name="s", extra={"serve_schedule": base.serve_schedule()}))
    assert report.by_rule("SERVE-PREFILL-STALL"), \
        base.serve_schedule()
    assert base.stats.prefill_stall_syncs >= 1

    def run(ragged):
        dec = PagedGPTDecoder(model, num_pages=16, page_size=16,
                              max_batch=2)
        eng = ContinuousBatchingEngine(dec, max_new_tokens=10, k_max=4,
                                       ragged=ragged, chunk_tokens=8)
        for p in ([1, 2, 3], list(range(1, 25)), [7, 8]):
            eng.submit(np.asarray(p, np.int32))
        eng.run()
        return eng

    ragged = run(ragged=True)
    report2 = pm.run(program, AnalysisContext(
        name="s", extra={"serve_schedule": ragged.serve_schedule()}))
    assert report2.by_rule("SERVE-PREFILL-STALL") == [], \
        ragged.serve_schedule()
    m = report2.metrics["prefill-stall"]
    assert m["n_prefill_syncs"] == 0 and m["n_mixed_horizons"] >= 1
    assert ragged.stats.prefill_syncs == 0
    assert ragged.stats.prefill_stall_syncs == 0


# ---------------------------------------------- fused multi-step training


def _tiny_trainer(donate=True):
    from paddle_tpu.distributed.trainer import Trainer

    paddle.seed(0)
    build_mesh(dp=1)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def loss_fn(m, b):
        return ((m(paddle.to_tensor(b["x"]))) ** 2).mean()

    opt = paddle.optimizer.AdamW(learning_rate=1e-3)
    return Trainer(model, opt, loss_fn, donate=donate)


def test_training_rule_clean_on_real_fused_step():
    """The REAL Trainer.step_multi capture (analysis_program(n=4)) is
    fully device-resident: zero host transfers, donated carry, the N
    ticks lowered to a device loop."""
    tr = _tiny_trainer()
    batch = {"x": np.zeros((4, 8), np.float32)}
    program = tr.analysis_program(batch, n=4)
    pm = PassManager(["training"])
    ctx = AnalysisContext(name="train", extra={"train_multi": True})
    report = pm.run(program, ctx)
    assert report.by_rule("HOST-SYNC-TRAIN") == [], \
        [str(f) for f in report.findings]
    m = report.metrics["training"]
    assert m["checked"] and m["carry_donated"]
    assert m["n_host_transfers"] == 0
    assert m["n_device_loops"] >= 1

    # scope: the same program outside a train-multi context never fires
    report2 = pm.run(program, AnalysisContext(name="train"))
    assert report2.by_rule("HOST-SYNC-TRAIN") == []
    assert report2.metrics["training"] == {"checked": False}


def test_training_rule_catches_host_fetch_in_scan_body():
    """HOST-SYNC-TRAIN planted defect: a host callback smuggled into the
    fused train scan is the per-step round-trip the device-resident
    horizon exists to kill."""
    def fused_with_callback(params, batches):
        def tick(p, b):
            loss = ((b @ p) ** 2).mean()
            jax.debug.print("loss {l}", l=loss)     # the planted defect
            return p - 0.1 * b.T @ (b @ p), loss
        params, losses = jax.lax.scan(tick, params, batches)
        return params, losses

    program = lower_callable(fused_with_callback,
                             jnp.zeros((8, 4), jnp.float32),
                             jnp.zeros((4, 2, 8), jnp.float32),
                             name="train_multi")
    pm = PassManager(["training"])
    ctx = AnalysisContext(name="train", extra={"train_multi": True})
    report = pm.run(program, ctx)
    hits = report.by_rule("HOST-SYNC-TRAIN")
    assert hits and any("host transfer" in h.message for h in hits)
    assert all(h.severity == Severity.ERROR for h in hits)
    assert report.metrics["training"]["n_host_transfers"] >= 1

    def clean(params, batches):
        def tick(p, b):
            loss = ((b @ p) ** 2).mean()
            return p - 0.1 * b.T @ (b @ p), loss
        return jax.lax.scan(tick, params, batches)

    program2 = lower_callable(clean, jnp.zeros((8, 4), jnp.float32),
                              jnp.zeros((4, 2, 8), jnp.float32),
                              name="train_multi")
    report2 = pm.run(program2, ctx)
    assert report2.by_rule("HOST-SYNC-TRAIN") == []
    assert report2.metrics["training"]["n_host_transfers"] == 0


def test_training_rule_catches_undonated_carry():
    """Trainer(donate=False)'s fused capture double-buffers the whole
    model state every horizon — an ERROR in the hot loop (the MEM-NO-
    DONATION warning composes the same way SERVE-HOST-SYNC-DECODE
    composes with MEM-NO-DONATION-KVCACHE)."""
    tr = _tiny_trainer(donate=False)
    batch = {"x": np.zeros((4, 8), np.float32)}
    program = tr.analysis_program(batch, n=4)
    pm = PassManager(["training"])
    ctx = AnalysisContext(name="train", extra={"train_multi": True})
    report = pm.run(program, ctx)
    hits = report.by_rule("HOST-SYNC-TRAIN")
    assert hits and hits[0].severity == Severity.ERROR
    assert any("not donated" in h.message for h in hits)
    assert not report.metrics["training"]["carry_donated"]


# ----------------------------------------------------- page refcounts


def _consistent_ledger():
    """8-page pool, scratch=7: pages 0-1 free, slot 0 holds [2, 3]
    with 2 cache-shared (refs 1), slot 1 holds [4, 5], page 6 parked
    (refcount 0) in the cache. Host-tier rows (tiered KV): one
    host-only spilled entry, and one restored entry whose device twin
    is the parked page 6."""
    return {"num_pages": 8, "scratch": 7, "free": [0, 1],
            "slots": {0: [2, 3], 1: [4, 5]},
            "shared": {0: [2]},
            "cache": {2: {"refs": 1, "parked": False},
                      6: {"refs": 0, "parked": True}},
            "host": {"aa01": {"bytes": 4096, "page": None},
                     "bb02": {"bytes": 4096, "page": 6}}}


def test_page_refcount_rule_clean_on_consistent_ledger():
    """MEM-PAGE-REFCOUNT stays silent when every allocatable page is
    owned exactly once (free XOR slot-held XOR parked), and is scoped:
    without extra["page_ledger"] the analyzer never fires."""
    pm = PassManager(["page-refcount"])
    prog = LoweredProgram("", name="ledger")
    ctx = AnalysisContext(name="ledger",
                          extra={"page_ledger": _consistent_ledger()})
    report = pm.run(prog, ctx)
    assert report.by_rule("MEM-PAGE-REFCOUNT") == [], str(report)
    m = report.metrics["page-refcount"]
    assert m["checked"] and m["n_pages"] == 8
    assert m["n_cached"] == 2 and m["n_parked"] == 1
    assert m["refcount_total"] == 1
    assert m["n_host"] == 2 and m["host_bytes"] == 8192
    # scope: no ledger -> not this analyzer's business
    report2 = pm.run(prog, AnalysisContext(name="ledger"))
    assert report2.metrics["page-refcount"] == {"checked": False}


@pytest.mark.parametrize("mutate, expect", [
    # double free: a page returned to the pool twice
    (lambda lg: lg["free"].append(0), "twice in the free list"),
    # double free: freed while a slot still holds it
    (lambda lg: lg["free"].append(3), "both free and held"),
    # double free: evicted page returned to free without unmapping
    (lambda lg: lg["free"].append(6), "both free and cache-tracked"),
    # leak: a held page vanishes from every ledger column
    (lambda lg: lg["slots"][1].remove(5), "leak"),
    # refcount drift: cache thinks two holders, only one slot mounts it
    (lambda lg: lg["cache"][2].update(refs=2), "refcount drift"),
    # aliasing: two slots hold one page with no covering refcount
    (lambda lg: lg["slots"][1].append(3), "unaccounted aliasing"),
    # shared-marked page the cache never tracked
    (lambda lg: lg["shared"][0].append(3), "does not track"),
    # reference dropped without decref: slot still maps a parked page
    (lambda lg: lg["slots"][1].append(6), "reference dropped"),
    # tiered KV: a host entry's device twin sits on the free list —
    # the eviction freed the page but dropped the tier's unmount
    # bookkeeping (a later prefill would overwrite an "advertised"
    # mounted twin)
    (lambda lg: lg["host"].update(
        cc03={"bytes": 4096, "page": 1}),
     "both host-resident and device-free"),
    # tiered KV: a host entry's twin backref points at a page the
    # cache no longer tracks (stale restore backref)
    (lambda lg: lg["host"].update(
        dd04={"bytes": 4096, "page": 3}),
     "stale restore backref"),
])
def test_page_refcount_rule_catches_planted_defects(mutate, expect):
    """Each corruption of the shared-pool ledger — double free, leak,
    refcount drift, unaccounted aliasing — is an ERROR (the
    prove-the-auditor half of the refcounted prefix cache)."""
    lg = _consistent_ledger()
    mutate(lg)
    pm = PassManager(["page-refcount"])
    report = pm.run(LoweredProgram("", name="ledger"),
                    AnalysisContext(name="ledger",
                                    extra={"page_ledger": lg}))
    hits = report.by_rule("MEM-PAGE-REFCOUNT")
    assert hits and all(h.severity == Severity.ERROR for h in hits)
    assert any(expect in h.message for h in hits), \
        (expect, [h.message for h in hits])


# ------------------------------------------------------- kv-quant rules

def _kv8_decoder(num_pages=8):
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import PagedGPTDecoder
    paddle.seed(0)
    build_mesh(dp=1)
    model = GPT(gpt_tiny(max_seq_len=64, dtype="float32", remat=False))
    model.eval()
    return PagedGPTDecoder(model, num_pages=num_pages, page_size=16,
                           max_batch=2, kv_quant="int8")


def _kv8_ctx(dec):
    cfg = dec.cfg
    return AnalysisContext(
        name="decode_kv8",
        extra={"serving_decode": True, "kv_quant": "int8",
               "kv_pool_block_elems": (dec.num_pages * dec.page_size *
                                       cfg.num_heads * cfg.head_dim)})


def test_kv_quant_rule_catches_dequantized_pool_in_hbm():
    """DTYPE-KV-DEQUANT-HBM planted defect: a decode step that
    dequantizes the WHOLE int8 pool up front (convert + scale multiply
    at full pool shape) re-materializes the bf16-width byte stream the
    int8 pool exists to delete. The real capture — dequant inside the
    shared per-page attention update — stays clean."""
    dec = _kv8_decoder()
    ctx = _kv8_ctx(dec)
    pm = PassManager(["kv-quant"])

    good = dec.analysis_program(k=2)
    report = pm.run(good, ctx)
    assert report.by_rule("DTYPE-KV-DEQUANT-HBM") == []
    assert report.by_rule("DTYPE-KV-SCALE-WIDTH") == []
    m = report.metrics["kv-quant"]
    assert m["checked"] and m["n_pool_dequants"] == 0
    assert m["n_scale_planes"] == 2          # K and V planes

    def bad_step(weights, k_pages, v_pages, tokens, lens, table, kids):
        (kq, ks), (vq, vs) = k_pages, v_pages
        kf = kq.astype(jnp.float32) * ks[..., None, None]  # FULL pool
        vf = vq.astype(jnp.float32) * vs[..., None, None]  # in HBM
        return dec._decode_step(weights, kf, vf, tokens, lens, table,
                                kids)

    from paddle_tpu.analysis.lowering import tree_arg_infos
    S = dec.max_batch
    args = (dec.weights, dec.k_pages, dec.v_pages,
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S, dec.max_pages), jnp.int32),
            jnp.arange(S, dtype=jnp.int32))
    traced = jax.jit(bad_step).trace(*args)   # donation is irrelevant
    # to this rule (arg_infos below still mark the cache donated)
    infos = tree_arg_infos(dec.weights, "param")
    infos += tree_arg_infos(dec.k_pages, "cache", prefix="k_pages",
                            donated=True)
    infos += tree_arg_infos(dec.v_pages, "cache", prefix="v_pages",
                            donated=True)
    bad = LoweredProgram(traced.lower().as_text(), jaxpr=traced.jaxpr,
                         name="bad_dequant", arg_infos=infos)
    report2 = pm.run(bad, ctx)
    hits = report2.by_rule("DTYPE-KV-DEQUANT-HBM")
    assert hits and all(h.severity == Severity.ERROR for h in hits)
    assert report2.metrics["kv-quant"]["n_pool_dequants"] >= 2  # K and V

    # scope: without extra["kv_quant"] the rule never fires
    report3 = pm.run(bad, AnalysisContext(name="decode"))
    assert report3.by_rule("DTYPE-KV-DEQUANT-HBM") == []
    assert report3.metrics["kv-quant"] == {"checked": False}


def test_kv_quant_rule_catches_non_f32_scale_plane():
    """DTYPE-KV-SCALE-WIDTH planted defect: a scale plane stored at any
    width other than f32 (f64 doubles the metadata stream; bf16
    quantizes the scales themselves) is an ERROR on the cache args."""
    dec = _kv8_decoder()
    ctx = _kv8_ctx(dec)
    pm = PassManager(["kv-quant"])
    # corrupt the live pool: K scale plane left bf16 (f64 is spelled
    # the same way in the rule — any non-f32 floating cache leaf)
    kq, ks = dec.k_pages
    dec.k_pages = (kq, ks.astype(jnp.bfloat16))
    bad = dec.analysis_program(k=2)
    report = pm.run(bad, ctx)
    hits = report.by_rule("DTYPE-KV-SCALE-WIDTH")
    assert hits and hits[0].severity == Severity.ERROR
    assert "bfloat16" in hits[0].message
    assert report.metrics["kv-quant"]["n_bad_scale_planes"] == 1


def _kv4_decoder(num_pages=8):
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import PagedGPTDecoder
    paddle.seed(0)
    build_mesh(dp=1)
    model = GPT(gpt_tiny(max_seq_len=64, dtype="float32", remat=False))
    model.eval()
    return PagedGPTDecoder(model, num_pages=num_pages, page_size=16,
                           max_batch=2, kv_quant="int4")


def _kv4_ctx(dec):
    cfg = dec.cfg
    return AnalysisContext(
        name="decode_kv4",
        extra={"serving_decode": True, "kv_quant": "int4",
               "kv_pool_block_elems": (dec.num_pages * dec.page_size *
                                       cfg.num_heads * cfg.head_dim)})


def test_kv_quant_dequant_rule_reproves_on_packed_int4_pool():
    """DTYPE-KV-DEQUANT-HBM re-proven on the nibble-packed layout: a
    whole-pool int4 dequant still funnels through an i8 -> wide-float
    convert at full pool shape (the nibble unpack lands in int8 BEFORE
    the float convert; the uint8 bit-twiddling itself is integer-only
    and can never match), so the same regex catches it. The real
    capture — per-page unpack next to the shared attention update, a
    page-sized convert — stays clean."""
    from paddle_tpu.serving.decoder import _dequantize_kv_int4
    dec = _kv4_decoder()
    ctx = _kv4_ctx(dec)
    pm = PassManager(["kv-quant"])

    good = dec.analysis_program(k=2)
    report = pm.run(good, ctx)
    assert report.by_rule("DTYPE-KV-DEQUANT-HBM") == []
    assert report.by_rule("DTYPE-KV-SCALE-WIDTH") == []
    m = report.metrics["kv-quant"]
    assert m["checked"] and m["kv_quant"] == "int4"
    assert m["n_pool_dequants"] == 0
    assert m["n_scale_planes"] == 2          # K and V group planes

    hd = (dec.cfg.num_heads, dec.cfg.head_dim)

    def bad_step(weights, k_pages, v_pages, tokens, lens, table, kids):
        (kq, ks), (vq, vs) = k_pages, v_pages
        kf = _dequantize_kv_int4(kq, ks, hd)     # FULL pool in HBM
        vf = _dequantize_kv_int4(vq, vs, hd)
        return dec._decode_step(weights, kf, vf, tokens, lens, table,
                                kids)

    from paddle_tpu.analysis.lowering import tree_arg_infos
    S = dec.max_batch
    args = (dec.weights, dec.k_pages, dec.v_pages,
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S, dec.max_pages), jnp.int32),
            jnp.arange(S, dtype=jnp.int32))
    traced = jax.jit(bad_step).trace(*args)
    infos = tree_arg_infos(dec.weights, "param")
    infos += tree_arg_infos(dec.k_pages, "cache", prefix="k_pages",
                            donated=True)
    infos += tree_arg_infos(dec.v_pages, "cache", prefix="v_pages",
                            donated=True)
    bad = LoweredProgram(traced.lower().as_text(), jaxpr=traced.jaxpr,
                         name="bad_dequant4", arg_infos=infos)
    report2 = pm.run(bad, ctx)
    hits = report2.by_rule("DTYPE-KV-DEQUANT-HBM")
    assert hits and all(h.severity == Severity.ERROR for h in hits)
    assert report2.metrics["kv-quant"]["n_pool_dequants"] >= 2


def test_kv_quant_scale_rule_reproves_on_packed_int4_pool():
    """DTYPE-KV-SCALE-WIDTH re-proven on the packed layout: an int4
    GROUP-scale plane cast to bf16 (quantizing the scales themselves)
    is an ERROR on the cache args, exactly like the int8 per-token
    plane."""
    dec = _kv4_decoder()
    ctx = _kv4_ctx(dec)
    pm = PassManager(["kv-quant"])
    kq, ks = dec.k_pages
    dec.k_pages = (kq, ks.astype(jnp.bfloat16))
    bad = dec.analysis_program(k=2)
    report = pm.run(bad, ctx)
    hits = report.by_rule("DTYPE-KV-SCALE-WIDTH")
    assert hits and hits[0].severity == Severity.ERROR
    assert "bfloat16" in hits[0].message
    assert report.metrics["kv-quant"]["n_bad_scale_planes"] == 1


def test_page_refcount_audit_catches_cow_without_scales():
    """MEM-PAGE-REFCOUNT scale audit planted defect: a copy-on-write
    that moves a page's int8 BYTES but not its scale plane leaves the
    private copy dequantizing against zero scales (garbage tokens).
    The engine's audit_pages() cross-checks bytes against scales on
    every held page; the healthy CoW (copy_page tree-maps bytes AND
    scales) audits clean. Audited MID-RUN (run(on_sync=...)): after
    the drain the CoW'd page is back on the free list and out of the
    audit's held set — exactly when the garbage has already been
    served."""
    from paddle_tpu.serving import ContinuousBatchingEngine, PrefixCache

    def run_workload(break_cow):
        dec = _kv8_decoder(num_pages=16)
        if break_cow:
            def bytes_only_copy(src, dst):
                (kq, ks), (vq, vs) = dec.k_pages, dec.v_pages
                kq = kq.at[:, dst].set(kq[:, src])
                vq = vq.at[:, dst].set(vq[:, src])
                dec.k_pages = (kq, ks)       # scales left behind
                dec.v_pages = (vq, vs)
            dec.copy_page = bytes_only_copy
        eng = ContinuousBatchingEngine(
            dec, max_new_tokens=2, k_max=2,
            prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint()))
        base = list(range(1, 17))            # one full shareable block
        hits = []
        for tail in ([21, 22], []):          # insert, then a FULL hit
            eng.submit(np.asarray(base + tail, np.int32))
            eng.run(on_sync=lambda e: hits.extend(e.audit_pages()))
        return eng, hits

    clean, clean_hits = run_workload(break_cow=False)
    assert clean.stats.prefix_cow >= 1       # the CoW really happened
    assert clean_hits == []
    assert clean.audit_pages() == []         # drained state clean too

    broken, broken_hits = run_workload(break_cow=True)
    assert broken.stats.prefix_cow >= 1
    assert broken_hits
    assert all(h.severity == Severity.ERROR for h in broken_hits)
    assert any("scale plane" in h.message for h in broken_hits)


# ------------------------------------------------------ schedule doctor


def _sched_program(fn, *args, axes=(("tp", 8),)):
    """LoweredProgram over a jaxpr traced under a named-axis env (the
    schedule pass consumes the jaxpr only; the HLO text stays empty)."""
    jx = jax.make_jaxpr(fn, axis_env=list(axes))(*args)
    return LoweredProgram("", jaxpr=jx,
                          name=getattr(fn, "__name__", "sched"))


def test_coll_serialized_rule_planted_defect_and_overlappable_twin():
    """COLL-SERIALIZED planted defect: a psum whose ONLY compute is its
    own producer (psum-after-dot, nothing else in flight) sits on the
    critical path with zero concurrently-schedulable compute — ERROR.
    The overlappable twin (an independent dot big enough to hide the
    wire) stays silent, and its schedule estimate prices the step at
    the roofline max while the serialized one prices toward the serial
    sum — bracketed either way."""
    from paddle_tpu.analysis import estimate_schedule

    def serialized(x, w):
        return jax.lax.psum(x @ w, "tp")

    def overlappable(x, w, w2):
        y = jax.lax.psum(x @ w, "tp")
        z = (x @ w2).sum()            # independent: schedulable DURING
        return y, z                   # the psum's wire time

    x = jnp.zeros((128, 256), jnp.float32)
    w = jnp.zeros((256, 128), jnp.float32)
    w2 = jnp.zeros((256, 2048), jnp.float32)
    pm = PassManager(["schedule"])

    bad = pm.run(_sched_program(serialized, x, w),
                 AnalysisContext(name="ser", mesh_axes={"tp": 8}))
    hits = bad.by_rule("COLL-SERIALIZED")
    assert hits and hits[0].severity == Severity.ERROR
    assert "psum" in hits[0].message and "serial" in hits[0].message
    m = bad.metrics["schedule"]
    assert m["n_collectives"] == 1
    assert m["n_serialized_collectives"] == 1
    # nothing overlaps: the overlap-aware step sits at the serial sum
    assert m["overlap_step_us"] == m["serial_step_us"]
    assert m["overlap_frac"] == 0.0

    good = pm.run(_sched_program(overlappable, x, w, w2),
                  AnalysisContext(name="ov", mesh_axes={"tp": 8}))
    assert good.by_rule("COLL-SERIALIZED") == []
    mg = good.metrics["schedule"]
    assert mg["n_collectives"] == 1
    assert mg["overlap_frac"] == 1.0
    assert mg["overlap_step_us"] == mg["ideal_step_us"]

    # the bracket is definitional on BOTH programs
    for est in (estimate_schedule(_sched_program(serialized, x, w),
                                  mesh_axes={"tp": 8}),
                estimate_schedule(_sched_program(overlappable, x, w, w2),
                                  mesh_axes={"tp": 8})):
        assert est.ideal_step_s <= est.overlap_step_s \
            <= est.serial_step_s + 1e-18


def test_coll_serialized_threshold_and_degenerate_group():
    """The hide bar is a context knob: compute covering 30% of the wire
    flags at the default 50% bar but passes a 20% bar. A degenerate
    1-participant psum has no wire leg at all — never a collective
    stream node, never a finding."""

    def partial(x, w, w2):
        y = jax.lax.psum(x @ w, "tp")     # wire >> the small free dot
        z = (x[:8] @ w2).sum()
        return y, z

    x = jnp.zeros((256, 64), jnp.float32)
    w = jnp.zeros((64, 1024), jnp.float32)
    w2 = jnp.zeros((64, 32), jnp.float32)
    pm = PassManager(["schedule"])
    program = _sched_program(partial, x, w, w2)

    strict = pm.run(program, AnalysisContext(name="p",
                                             mesh_axes={"tp": 8}))
    assert strict.by_rule("COLL-SERIALIZED")
    loose = pm.run(program, AnalysisContext(
        name="p", mesh_axes={"tp": 8}, schedule_hide_frac=0.001))
    assert loose.by_rule("COLL-SERIALIZED") == []

    def degenerate(x, w):
        return jax.lax.psum(x @ w, "one")

    deg = pm.run(_sched_program(degenerate, x, w, axes=(("one", 1),)),
                 AnalysisContext(name="d", mesh_axes={"one": 1}))
    assert deg.by_rule("COLL-SERIALIZED") == []
    assert deg.metrics["schedule"]["n_collectives"] == 0
    assert deg.metrics["schedule"]["overlap_frac"] == 1.0


def test_coll_serialized_scan_body_collective_attributed_to_source():
    """A collective INSIDE a scan body is still found (the DAG walk
    recurses like the memory pass's liveness walk), its cost scales
    with the trip count, and the finding attributes it to the source
    line of the psum call — not to the scan eqn that hides it."""
    from paddle_tpu.analysis import estimate_schedule

    def body(c, xs):
        y = c @ xs
        y = jax.lax.psum(y, "tp")     # <-- the line the rule must name
        return y, y.sum()
    psum_line = body.__code__.co_firstlineno + 2

    def f(c0, xs):
        return jax.lax.scan(body, c0, xs)

    c0 = jnp.zeros((64, 64), jnp.float32)
    xs = jnp.zeros((6, 64, 64), jnp.float32)
    pm = PassManager(["schedule"])
    report = pm.run(_sched_program(f, c0, xs),
                    AnalysisContext(name="scan", mesh_axes={"tp": 8}))
    hits = report.by_rule("COLL-SERIALIZED")
    assert hits, "scan-body collective not found"
    assert f"test_analysis_rules.py:{psum_line}" in hits[0].op, \
        (hits[0].op, psum_line)
    # trip scaling: the same body over 12 steps prices exactly 2x wire
    est6 = estimate_schedule(_sched_program(f, c0, xs),
                             mesh_axes={"tp": 8})
    est12 = estimate_schedule(
        _sched_program(f, c0, jnp.zeros((12, 64, 64), jnp.float32)),
        mesh_axes={"tp": 8})
    assert est12.wire_s == pytest.approx(2 * est6.wire_s)


def test_roofline_drift_verdict_splits_serialized_from_mispriced():
    """The drift ledger's serialized-vs-mispriced verdict: ticks that
    carry predicted_serial_s (engines/Trainer stamp the serial sum of
    the priced legs next to the overlapped max) let the analyzer tell
    a schedule that SERIALIZED its streams (measured inside the serial
    sum — fix the schedule, not the pricing inputs) from a genuinely
    mispriced leg (measured outside even the sum). Ticks without the
    serial band keep the legacy re-fit message."""
    from paddle_tpu.serving import FlightRecorder
    program = lower_callable(lambda x: x + 1.0,
                             jnp.zeros((2,), jnp.float32), name="decode")
    pm = PassManager(["roofline-drift"])

    def ledger(meas, serial):
        rec = FlightRecorder()
        for _ in range(4):
            rec.tick("serve", ("ragged", 4, 8), measured_s=meas,
                     predicted_s=1e-4, predicted_serial_s=serial)
        return rec.drift_report()

    # measured 10x the overlapped price but INSIDE the serial sum
    serialized = ledger(1e-3, 1.1e-3)
    assert serialized[0]["verdict"] == "serialized"
    rep = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": serialized}))
    hits = rep.by_rule("ROOFLINE-DRIFT")
    assert hits and hits[0].severity == Severity.ERROR
    assert "SERIALIZES" in hits[0].message
    assert "COLL-SERIALIZED" in hits[0].suggested_fix
    assert rep.metrics["roofline-drift"]["n_serialized"] == 1

    # measured far outside even the serial sum: a real mispricing
    mispriced = ledger(1e-2, 1.1e-3)
    assert mispriced[0]["verdict"] == "mispriced"
    rep2 = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": mispriced}))
    hits2 = rep2.by_rule("ROOFLINE-DRIFT")
    assert hits2 and "underprices" in hits2[0].message
    assert rep2.metrics["roofline-drift"]["n_serialized"] == 0

    # no serial band on the ticks: legacy message, no verdict claim
    rec = FlightRecorder()
    for _ in range(4):
        rec.tick("serve", ("decode", 4, 1), measured_s=1e-3,
                 predicted_s=1e-4)
    legacy = rec.drift_report()
    assert legacy[0]["verdict"] == "mispriced"
    assert "predicted_serial_s" not in legacy[0]
    rep3 = pm.run(program, AnalysisContext(
        name="s", extra={"roofline_drift": legacy}))
    assert rep3.by_rule("ROOFLINE-DRIFT")
    assert "underprices" in rep3.by_rule("ROOFLINE-DRIFT")[0].message


def test_schedule_prices_cond_at_its_most_expensive_branch():
    """Mutually exclusive cond branches must not SUM (exactly one
    executes — the eqn_flops rule): a cond over two dot branches
    prices like one dot, not two, and an untaken branch's compute
    never counts as COLL-SERIALIZED-hideable work next to a
    serialized collective."""
    from paddle_tpu.analysis import estimate_schedule

    w = jnp.zeros((256, 256), jnp.float32)
    x = jnp.zeros((256, 256), jnp.float32)

    def one_dot(p, x, w):
        return x @ w

    def cond_dots(p, x, w):
        return jax.lax.cond(p, lambda a: a @ w, lambda a: a @ w + 1.0,
                            x)

    e1 = estimate_schedule(_sched_program(one_dot, True, x, w))
    e2 = estimate_schedule(_sched_program(cond_dots, True, x, w))
    # exactly ONE branch's dot is priced (flops ~= one dot + the add's
    # elementwise tail; the pre-fix sum counted both dots, ~2.7x the
    # single-dot compute — now the heavier branch alone, < 2x)
    assert e2.flops < 1.1 * e1.flops, (e2.flops, e1.flops)
    assert e2.compute_s < 2.0 * e1.compute_s, (e2.compute_s,
                                               e1.compute_s)

    def serialized_with_cond(p, x, w, wc):
        y = jax.lax.psum(x @ w, "tp")
        z = jax.lax.cond(p, lambda a: (a @ wc).sum(),
                         lambda a: ((a @ wc) * 2.0).sum(), x)
        return y, z

    wc = jnp.zeros((256, 2048), jnp.float32)
    pm = PassManager(["schedule"])
    rep = pm.run(_sched_program(serialized_with_cond, True, x, w, wc),
                 AnalysisContext(name="c", mesh_axes={"tp": 8}))
    # the taken branch's dot IS hideable (independent of the psum): no
    # flag — but only ONE branch's worth of compute was credited
    assert rep.by_rule("COLL-SERIALIZED") == []
    m = rep.metrics["schedule"]
    assert m["n_collectives"] == 1
