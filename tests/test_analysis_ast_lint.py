"""Dy2static AST linter coverage over the r5 fuzz corpus shapes: the
loop-target leak (the fuzzer's silent-wrong-numbers find), early
returns, traced-value branches — each flagged with its rule id — plus
the unconvertible shapes (global write, return-in-try) and the
must-stay-silent clean program.

Programs are written to real module files where needed so the SAME
function object feeds both the linter and convert_to_static — proving
the linter flags exactly what the converter then handles (eager ==
converted on the hazardous shapes it marks as handled).
"""
import importlib.util

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import Severity, lint_function


def _rules(src):
    return {f.rule_id for f in lint_function(src).findings}


# ------------------------------------------------------- corpus shapes

LOOP_TARGET_LEAK = """
def f(x):
    for j in range(3):
        x = x * 1.1
    if j % 2 == 0:
        x = x + 1.0
    return x
"""

NESTED_SHADOW_LEAK = """
def f(x):
    for j in range(2):
        for j in range(3):
            x = x + 0.1
        x = x * (j + 1)
    return x
"""

EARLY_RETURN = """
def f(x):
    if paddle.sum(x) > 0:
        return x * 2.0
    return x - 0.25
"""

TRACED_BRANCH = """
def f(x):
    if paddle.sum(x) > 0:
        x = x + 1.0
    return x
"""

CLEAN = """
def f(x):
    y = x * 2.0
    z = y + paddle.sum(y) * 0.01
    return z - 0.25
"""


def test_loop_target_leak_flagged():
    rep = lint_function(LOOP_TARGET_LEAK)
    leaks = rep.by_rule("D2S-LOOP-TARGET-LEAK")
    assert len(leaks) == 1
    assert leaks[0].severity == Severity.WARNING
    assert "`j`" in leaks[0].message


def test_nested_shadow_leak_flagged():
    """The exact r5 fuzzer shape: nested loops sharing one target name —
    the INNER loop's target leaks into the outer body's reads."""
    rep = lint_function(NESTED_SHADOW_LEAK)
    assert rep.by_rule("D2S-LOOP-TARGET-LEAK")


def test_early_return_flagged():
    rules = _rules(EARLY_RETURN)
    assert "D2S-EARLY-RETURN" in rules
    # the condition reads x -> also a traced branch
    assert "D2S-TRACED-BRANCH" in rules


def test_traced_value_branch_flagged():
    rep = lint_function(TRACED_BRANCH)
    hits = rep.by_rule("D2S-TRACED-BRANCH")
    assert len(hits) == 1
    assert hits[0].severity == Severity.INFO
    assert "lax.cond" in hits[0].message


def test_clean_program_zero_findings():
    rep = lint_function(CLEAN)
    assert rep.findings == [], [str(f) for f in rep.findings]


def test_derived_value_taint_propagates():
    src = """
def f(x):
    y = x * 2.0
    z = y - 1.0
    while z.sum() > 0:
        z = z - 1.0
    return z
"""
    assert "D2S-TRACED-BRANCH" in _rules(src)


def test_concrete_branch_not_flagged():
    src = """
def f(x, n):
    for i in range(4):
        if i % 2 == 0:
            pass
    return x
"""
    # i derives from range(4) (concrete), so no traced-branch finding;
    # n IS a parameter and `i % 2` must not alias it
    rep = lint_function(src)
    assert rep.by_rule("D2S-TRACED-BRANCH") == []


# -------------------------------------------------- unconvertible shapes

def test_nested_scope_hazards_not_misattributed():
    """A `global`/`return` inside a NESTED helper belongs to the
    helper's own conversion, not the forward being linted — it must not
    fail the outer function's lint (the outer fn converts fine)."""
    src = """
def f(x):
    def bump():
        global _calls
        _calls = 1
        return None
    y = [v * 2.0 for v in [x]]
    return y[0]
"""
    rep = lint_function(src)
    assert rep.by_rule("D2S-GLOBAL-WRITE") == [], \
        [str(f) for f in rep.findings]
    assert rep.by_rule("D2S-EARLY-RETURN") == []


def test_global_write_is_error():
    src = """
def f(x):
    global _state
    _state = x
    return x
"""
    rep = lint_function(src)
    hits = rep.by_rule("D2S-GLOBAL-WRITE")
    assert hits and hits[0].severity == Severity.ERROR


def test_return_in_try_flagged():
    src = """
def f(x):
    try:
        return x * 2.0
    finally:
        pass
"""
    rep = lint_function(src)
    hits = rep.by_rule("D2S-RETURN-IN-TRY")
    assert hits and hits[0].severity == Severity.WARNING


def test_loop_else_flagged():
    src = """
def f(x):
    for i in range(3):
        x = x + 1.0
    else:
        x = x * 2.0
    return x
"""
    assert "D2S-LOOP-ELSE" in _rules(src)


# ------------------------------- linter agrees with the real converter

@pytest.mark.parametrize("src,expect_rule", [
    (LOOP_TARGET_LEAK, "D2S-LOOP-TARGET-LEAK"),
    (EARLY_RETURN, "D2S-EARLY-RETURN"),
    (TRACED_BRANCH, "D2S-TRACED-BRANCH"),
])
def test_flagged_shapes_still_convert_correctly(tmp_path, src,
                                                expect_rule):
    """Every 'handled' finding must be true to its word: the linter
    flags the shape AND the converter produces eager-equal results on
    it (the contract that rules stay INFO/WARNING, not ERROR)."""
    mod_file = tmp_path / f"lint_{expect_rule.lower().replace('-', '_')}.py"
    mod_file.write_text("import paddle_tpu as paddle\n" + src)
    spec = importlib.util.spec_from_file_location(mod_file.stem, mod_file)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = mod.f

    rep = lint_function(fn)
    assert rep.by_rule(expect_rule), [str(f) for f in rep.findings]
    assert not rep.errors    # handled shapes never lint as ERROR

    from paddle_tpu.jit.dy2static import convert_to_static
    conv = convert_to_static(fn)
    assert conv is not fn, "converter fell back on a handled shape"
    for v in (1.0, -2.0, 0.3):
        x = np.full((2,), v, "float32")
        want = fn(paddle.to_tensor(x)).numpy()
        got = conv(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_layer_forward_lintable():
    """lint_function accepts a Layer (lints its forward) — the
    to_static(lint=True) path."""
    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(4, 4)

        def forward(self, x):
            if x.sum() > 0:
                return self.fc(x)
            return x

    rep = lint_function(M())
    rules = {f.rule_id for f in rep.findings}
    assert "D2S-TRACED-BRANCH" in rules
    assert "D2S-EARLY-RETURN" in rules
