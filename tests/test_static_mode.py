"""Static-graph mode (Program/Executor) — reference python/paddle/static.

The rebuild compiles the fetched sub-graph as one XLA program instead of
interpreting an op-by-op ProgramDesc; these tests check behavioral parity:
feed/fetch, training via optimizer.minimize, jit-cache reuse.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(autouse=True)
def _static_guard():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_feed_fetch_forward():
    x = paddle.static.data("x_ff", [2, 3], "float32")
    y = x * 2.0 + 1.0
    exe = paddle.static.Executor()
    out = exe.run(feed={"x_ff": np.ones((2, 3), np.float32)}, fetch_list=[y])
    np.testing.assert_allclose(out[0], np.full((2, 3), 3.0), rtol=1e-6)


def test_layer_forward_and_multiple_fetch():
    x = paddle.static.data("x_mf", [4, 8], "float32")
    lin = paddle.nn.Linear(8, 2)
    h = lin(x)
    s = paddle.nn.functional.softmax(h)
    exe = paddle.static.Executor()
    xs = np.random.RandomState(0).randn(4, 8).astype("float32")
    h_np, s_np = exe.run(feed={"x_mf": xs}, fetch_list=[h, s])
    expect = xs @ np.asarray(lin.weight.numpy()) + np.asarray(lin.bias.numpy())
    np.testing.assert_allclose(h_np, expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s_np.sum(-1), np.ones(4), rtol=1e-5)


def test_minimize_trains_to_convergence():
    x = paddle.static.data("x_tr", [8, 3], "float32")
    y = paddle.static.data("y_tr", [8, 1], "float32")
    lin = paddle.nn.Linear(3, 1)
    loss = paddle.nn.functional.mse_loss(lin(x), y)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    opt.minimize(loss)
    exe = paddle.static.Executor()
    rng = np.random.RandomState(0)
    W = rng.randn(3, 1).astype("float32")
    xs = rng.randn(64, 3).astype("float32")
    ys = xs @ W
    first = last = None
    for i in range(150):
        idx = rng.randint(0, 64, 8)
        (lv,) = exe.run(feed={"x_tr": xs[idx], "y_tr": ys[idx]}, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first * 1e-3, (first, last)


def test_symbolic_var_refuses_numpy():
    x = paddle.static.data("x_nv", [2, 2], "float32")
    y = x + 1.0
    with pytest.raises(RuntimeError):
        y.numpy()


def test_missing_feed_raises():
    x = paddle.static.data("x_mr", [2, 2], "float32")
    y = x * 3.0
    exe = paddle.static.Executor()
    with pytest.raises(ValueError):
        exe.run(feed={}, fetch_list=[y])


def test_executor_cache_reuse():
    x = paddle.static.data("x_cr", [2, 2], "float32")
    y = x * 3.0
    exe = paddle.static.Executor()
    exe.run(feed={"x_cr": np.ones((2, 2), np.float32)}, fetch_list=[y])
    n = len(exe._cache)
    exe.run(feed={"x_cr": np.zeros((2, 2), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == n  # same signature → no recompile


def test_static_gradients():
    x = paddle.static.data("x_gr", [3], "float32")
    y = paddle.sum(x * x)
    (gx,) = paddle.static.gradients([y], [x])
    exe = paddle.static.Executor()
    xs = np.array([1.0, 2.0, 3.0], np.float32)
    (g,) = exe.run(feed={"x_gr": xs}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xs, rtol=1e-6)
