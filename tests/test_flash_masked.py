"""Masked + dropout flash attention (VERDICT #9b): the Pallas kernels must
handle additive/boolean masks, in-kernel dropout, GQA folding, and non-128
sequence lengths — verified in interpret mode against the jnp reference
(which shares the dropout hash, so even dropout compares exactly)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops.attention as A


def _mk(B=2, Lq=256, Lk=256, Hq=2, Hkv=2, D=64, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, Lq, Hq, D).astype(dtype) * 0.3)
    k = jnp.asarray(rng.randn(B, Lk, Hkv, D).astype(dtype) * 0.3)
    v = jnp.asarray(rng.randn(B, Lk, Hkv, D).astype(dtype) * 0.3)
    return q, k, v


def _cfg(causal, scale, rate=0.0, has_kvb=False, kvb_b=False,
         has_fb=False, fb_b=False, fb_h=False):
    return (causal, scale, rate, has_kvb, kvb_b, has_fb, fb_b, fb_h)


_D = np.zeros((1, 1), np.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_kvb_padding_mask_matches_reference(causal):
    q, k, v = _mk()
    sc = 0.125
    # padding mask: last 64 kv positions invalid, per-batch additive bias
    kvb = np.zeros((2, 256), np.float32)
    kvb[:, 192:] = -1e30
    kvb = jnp.asarray(kvb)
    cfg = _cfg(causal, sc, has_kvb=True, kvb_b=True)
    out, lse = A._fwd_lse_impl(q, k, v, kvb, _D, _D, cfg, interpret=True)
    ref = A.mha_reference(q, k, v, causal=causal, scale=sc,
                          attn_mask=kvb[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # backward
    g = jnp.asarray(np.random.RandomState(9).randn(*out.shape).astype(np.float32))
    dq, dk, dv = A._bwd_impl(q, k, v, lse, g, out, kvb, _D, _D, cfg, interpret=True)
    ref_grads = jax.vjp(lambda q, k, v: A.mha_reference(
        q, k, v, causal=causal, scale=sc, attn_mask=kvb[:, None, None, :]),
        q, k, v)[1](g)
    for got, want in zip((dq, dk, dv), ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-4, rtol=2e-3)


def test_full_additive_mask_matches_reference():
    q, k, v = _mk(B=2, Hq=2)
    sc = 0.125
    rng = np.random.RandomState(5)
    # random block mask per (batch, head) — e.g. document masking
    fb = jnp.asarray(np.where(rng.rand(2, 2, 256, 256) > 0.3, 0.0, -1e30)
                     .astype(np.float32))
    cfg = _cfg(False, sc, has_fb=True, fb_b=True, fb_h=True)
    out, lse = A._fwd_lse_impl(q, k, v, _D, fb, _D, cfg, interpret=True)
    ref = A.mha_reference(q, k, v, scale=sc, attn_mask=fb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g = jnp.asarray(rng.randn(*out.shape).astype(np.float32))
    dq, dk, dv = A._bwd_impl(q, k, v, lse, g, out, _D, fb, _D, cfg, interpret=True)
    ref_grads = jax.vjp(lambda q, k, v: A.mha_reference(
        q, k, v, scale=sc, attn_mask=fb), q, k, v)[1](g)
    for got, want in zip((dq, dk, dv), ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-4, rtol=2e-3)


def test_full_mask_gqa_prefold_matches_reference():
    # Hq=4, Hkv=2 with a per-query-head mask: dispatcher pre-folds the bias
    q, k, v = _mk(Hq=4, Hkv=2, Lq=128, Lk=128)
    sc = 0.125
    rng = np.random.RandomState(6)
    fb = jnp.asarray(np.where(rng.rand(2, 4, 128, 128) > 0.2, 0.0, -1e30)
                     .astype(np.float32))
    ref = A.mha_reference(q, k, v, scale=sc, attn_mask=fb)
    # public API path (runs the kernel in interpret mode on CPU)
    out = A.flash_attention(q, k, v, scale=sc, attn_mask=fb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bool_mask_and_broadcast_shapes():
    q, k, v = _mk(Lq=128, Lk=128)
    sc = 0.125
    rng = np.random.RandomState(7)
    mask_bool = jnp.asarray(rng.rand(2, 1, 1, 128) > 0.25)  # padding-style bool
    ref = A.mha_reference(q, k, v, scale=sc, attn_mask=mask_bool)
    out = A.flash_attention(q, k, v, scale=sc, attn_mask=mask_bool)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_nonmultiple_seq_len_padded():
    # L=100: the dispatcher pads to 128 and masks the tail
    q, k, v = _mk(Lq=100, Lk=100)
    sc = 0.125
    out = A.flash_attention(q, k, v, causal=True, scale=sc)
    ref = A.mha_reference(q, k, v, causal=True, scale=sc)
    assert out.shape == (2, 100, 2, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dropout_matches_reference_hash():
    """Kernel dropout and mha_reference share the hash — exact parity."""
    q, k, v = _mk()
    sc, rate = 0.125, 0.25
    seed = np.full((1, 1), 1234.0, np.float32)
    cfg = _cfg(False, sc, rate=rate)
    out, lse = A._fwd_lse_impl(q, k, v, _D, _D, jnp.asarray(seed), cfg,
                               interpret=True)
    ref = A.mha_reference(q, k, v, scale=sc, dropout_rate=rate,
                          dropout_seed=1234)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # different seed -> different output
    out2, _ = A._fwd_lse_impl(q, k, v, _D, _D,
                              jnp.asarray(np.full((1, 1), 77.0, np.float32)),
                              cfg, interpret=True)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_dropout_backward_matches_reference():
    q, k, v = _mk(B=1, Lq=128, Lk=128, Hq=2)
    sc, rate = 0.125, 0.2
    seed = jnp.asarray(np.full((1, 1), 42.0, np.float32))
    cfg = _cfg(True, sc, rate=rate)
    out, lse = A._fwd_lse_impl(q, k, v, _D, _D, seed, cfg, interpret=True)
    g = jnp.asarray(np.random.RandomState(11).randn(*out.shape).astype(np.float32))
    dq, dk, dv = A._bwd_impl(q, k, v, lse, g, out, _D, _D, seed, cfg,
                             interpret=True)
    ref_grads = jax.vjp(lambda q, k, v: A.mha_reference(
        q, k, v, causal=True, scale=sc, dropout_rate=rate, dropout_seed=42),
        q, k, v)[1](g)
    for got, want in zip((dq, dk, dv), ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-4, rtol=3e-3)


def test_dropout_rate_statistics():
    """Fraction of dropped attention entries ~ rate."""
    rng = np.random.RandomState(0)
    rows = jnp.arange(512, dtype=jnp.int32)
    cols = jnp.arange(512, dtype=jnp.int32)
    salt = A._drop_salt(jnp.uint32(99), 0, 0)
    keep = A._keep_tile(salt, rows, cols, 0.3)
    frac = float(np.asarray(keep).mean())
    assert abs(frac - 0.7) < 0.01


def test_sdpa_routes_mask_and_dropout():
    """F.scaled_dot_product_attention handles mask + dropout end-to-end."""
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    q, k, v = _mk(Lq=128, Lk=128)
    mask = jnp.asarray(np.random.RandomState(3).rand(2, 1, 1, 128) > 0.2)
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(np.asarray(q)), paddle.to_tensor(np.asarray(k)),
        paddle.to_tensor(np.asarray(v)), attn_mask=paddle.to_tensor(np.asarray(mask)),
        dropout_p=0.1, training=True)
    assert out.shape == [2, 128, 2, 64]
    assert np.isfinite(out.numpy()).all()
    # eval mode: deterministic, matches reference
    out_eval = F.scaled_dot_product_attention(
        paddle.to_tensor(np.asarray(q)), paddle.to_tensor(np.asarray(k)),
        paddle.to_tensor(np.asarray(v)), attn_mask=paddle.to_tensor(np.asarray(mask)),
        dropout_p=0.1, training=False)
    ref = A.mha_reference(q, k, v, attn_mask=mask)
    np.testing.assert_allclose(out_eval.numpy(), np.asarray(ref), atol=2e-5)
